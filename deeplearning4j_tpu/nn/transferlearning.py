"""Transfer learning: rebuild a trained net with frozen layers, new heads.

Reference: nn/transferlearning/TransferLearning.java:35 (Builder :62-275,
GraphBuilder :444-720), FineTuneConfiguration.java, TransferLearningHelper.java.

TPU-native mechanics: freezing wraps a layer config in ``FrozenLayer`` whose
forward stop-gradients its params — XLA then prunes the dead backward graph,
so frozen layers cost zero backward FLOPs (the reference instead zeroes
updates after computing them). Parameter transfer is pytree copying; replaced
layers re-initialise from the configured scheme.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, fields as dc_fields
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.builders import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration,
    GraphVertex,
    LayerVertex,
    topological_sort,
)
from deeplearning4j_tpu.nn.conf.layers.misc import FrozenLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclass
class FineTuneConfiguration:
    """Global hyperparameter overrides applied to every *unfrozen* layer and
    to the network config (reference: FineTuneConfiguration.java — only
    explicitly-set values override)."""

    seed: Optional[int] = None
    updater: Optional[object] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[float] = None
    learning_rate: Optional[float] = None

    def apply_to_layer(self, layer) -> None:
        if isinstance(layer, FrozenLayer):
            return  # frozen layers keep their config (reference parity)
        for f in ("activation", "weight_init", "bias_init", "l1", "l2",
                  "l1_bias", "l2_bias", "dropout", "learning_rate"):
            v = getattr(self, f)
            if v is not None and hasattr(layer, f):
                setattr(layer, f, v)

    def apply_to_conf(self, conf) -> None:
        if self.seed is not None:
            conf.seed = self.seed
        if self.updater is not None:
            conf.updater = copy.deepcopy(self.updater)
        elif self.learning_rate is not None and hasattr(conf.updater,
                                                        "learning_rate"):
            conf.updater.learning_rate = self.learning_rate


def _freeze(layer):
    return layer if isinstance(layer, FrozenLayer) else FrozenLayer(inner=layer)


class TransferLearning:
    """Namespace matching the reference's TransferLearning.Builder /
    TransferLearning.GraphBuilder entry points."""

    class Builder:
        """reference: TransferLearning.java:62-430 (MultiLayerNetwork)."""

        def __init__(self, orig: MultiLayerNetwork):
            self._orig = orig
            self._conf = copy.deepcopy(orig.conf)
            self._layers = list(self._conf.layers)
            # param source per kept layer: orig index, or None -> re-init
            self._sources = list(range(len(self._layers)))
            self._ftc: Optional[FineTuneConfiguration] = None
            self._frozen_till = -1

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def set_feature_extractor(self, layer_num: int):
            """Freeze layers [0, layer_num] (reference :87-99)."""
            self._frozen_till = layer_num
            return self

        def nout_replace(self, layer_num: int, n_out: int,
                         weight_init: Optional[str] = None, dist=None,
                         scheme_next: Optional[str] = None, dist_next=None):
            """Change nOut of layer_num; re-init it and the nIn side of the
            next parameterised layer (reference :101-198)."""
            layer = self._layers[layer_num]
            inner = layer.inner if isinstance(layer, FrozenLayer) else layer
            inner.n_out = n_out
            if weight_init is not None:
                inner.weight_init = weight_init
            if dist is not None:
                inner.weight_init = "distribution"
                inner.dist = dist
            self._sources[layer_num] = None
            # downstream: first layer with params needs new nIn/weights
            for j in range(layer_num + 1, len(self._layers)):
                nxt = self._layers[j]
                ninner = nxt.inner if isinstance(nxt, FrozenLayer) else nxt
                if hasattr(ninner, "n_in"):
                    ninner.n_in = 0  # re-infer at build
                if ninner.param_order():
                    if scheme_next is not None:
                        ninner.weight_init = scheme_next
                    if dist_next is not None:
                        ninner.weight_init = "distribution"
                        ninner.dist = dist_next
                    self._sources[j] = None
                    break
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            """Drop the last n layers (reference :199-226)."""
            if n <= 0:
                raise ValueError(f"remove_layers_from_output requires n >= 1, "
                                 f"got {n}")
            self._layers = self._layers[:-n]
            self._sources = self._sources[:-n]
            return self

        def add_layer(self, layer):
            """Append a new layer (reference :228-262)."""
            self._layers.append(layer)
            self._sources.append(None)
            return self

        def set_input_pre_processor(self, layer_idx: int, p):
            self._conf.preprocessors[layer_idx] = p
            return self

        def build(self) -> MultiLayerNetwork:
            layers = [copy.deepcopy(l) for l in self._layers]
            if self._frozen_till >= 0:
                layers = [(_freeze(l) if i <= self._frozen_till else l)
                          for i, l in enumerate(layers)]
            g = NeuralNetConfiguration(seed=self._conf.seed,
                                       updater=copy.deepcopy(self._conf.updater),
                                       dtype=self._conf.dtype)
            if self._ftc is not None:
                self._ftc.apply_to_conf(g)
                for l in layers:
                    self._ftc.apply_to_layer(l)
            builder = NeuralNetConfiguration.builder()
            builder._c = g
            lb = builder.list(*layers)
            if self._conf.input_type is not None:
                lb.set_input_type(self._conf.input_type)
            for i, p in self._conf.preprocessors.items():
                if i < len(layers):
                    lb.input_pre_processor(i, p)
            if self._conf.backprop_type == "tbptt":
                lb.t_bptt_lengths(self._conf.tbptt_fwd_length,
                                  self._conf.tbptt_back_length)
            new_conf = lb.build()
            net = MultiLayerNetwork(new_conf).init()
            # transfer params for kept layers
            for i, src in enumerate(self._sources):
                if src is not None:
                    net.params[str(i)] = jax.tree_util.tree_map(
                        lambda a: a, self._orig.params[str(src)])
                    if str(src) in self._orig.state:
                        net.state[str(i)] = jax.tree_util.tree_map(
                            lambda a: a, self._orig.state[str(src)])
            net.updater_state = new_conf.updater.init(net.params)
            return net

    class GraphBuilder:
        """reference: TransferLearning.java:444-720 (ComputationGraph)."""

        def __init__(self, orig: ComputationGraph):
            self._orig = orig
            self._conf = copy.deepcopy(orig.conf)
            self._copy_from = {n: n for n in self._conf.vertices}
            self._ftc: Optional[FineTuneConfiguration] = None
            self._frozen: set = set()

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def set_feature_extractor(self, *vertex_names):
            """Freeze the named vertices and all their ancestors
            (reference :480-497)."""
            conf = self._conf
            parents: dict = conf.vertex_inputs
            stack = list(vertex_names)
            while stack:
                n = stack.pop()
                if n in self._frozen or n in conf.network_inputs:
                    continue
                self._frozen.add(n)
                stack.extend(p for p in parents.get(n, ()))
            return self

        def nout_replace(self, vertex_name: str, n_out: int,
                         weight_init: Optional[str] = None, dist=None):
            """reference :499-610 (+ downstream nIn re-inference at build)."""
            v = self._conf.vertices[vertex_name]
            if not isinstance(v, LayerVertex):
                raise ValueError(f"'{vertex_name}' is not a layer vertex")
            v.layer.n_out = n_out
            if weight_init is not None:
                v.layer.weight_init = weight_init
            if dist is not None:
                v.layer.weight_init = "distribution"
                v.layer.dist = dist
            self._copy_from[vertex_name] = None
            # Downstream width change propagates through parameterless
            # vertices (ElementWise/Merge/Activation...) until it reaches
            # parameterised layers, which re-infer nIn and re-init (the MLN
            # builder's scan-to-next-parameterised-layer, generalised to DAGs)
            consumers: dict = {}
            for name, ins in self._conf.vertex_inputs.items():
                for i in ins:
                    consumers.setdefault(i, []).append(name)
            stack = list(consumers.get(vertex_name, ()))
            seen = set()
            while stack:
                name = stack.pop()
                if name in seen:
                    continue
                seen.add(name)
                c = self._conf.vertices[name]
                if isinstance(c, LayerVertex):
                    if hasattr(c.layer, "n_in"):
                        c.layer.n_in = 0
                    if c.layer.param_order():
                        self._copy_from[name] = None
                        continue  # parameterised layer absorbs the change
                stack.extend(consumers.get(name, ()))
            return self

        def remove_vertex_and_connections(self, name: str):
            """reference :623-634"""
            self._conf.vertices.pop(name)
            self._conf.vertex_inputs.pop(name)
            self._copy_from.pop(name, None)
            self._frozen.discard(name)
            for n, ins in list(self._conf.vertex_inputs.items()):
                if name in ins:
                    self.remove_vertex_and_connections(n)
            self._conf.network_outputs = [o for o in
                                          self._conf.network_outputs
                                          if o != name]
            return self

        def remove_vertex_keep_connections(self, name: str):
            """Remove a vertex, rewiring its consumers to its input
            (reference :612-621; valid for single-input vertices)."""
            ins = self._conf.vertex_inputs.pop(name)
            self._conf.vertices.pop(name)
            self._copy_from.pop(name, None)
            self._frozen.discard(name)
            if len(ins) != 1:
                raise ValueError("remove_vertex_keep_connections requires a "
                                 "single-input vertex")
            src = ins[0]
            for n, vins in self._conf.vertex_inputs.items():
                self._conf.vertex_inputs[n] = [src if i == name else i
                                               for i in vins]
            self._conf.network_outputs = [src if o == name else o
                                          for o in self._conf.network_outputs]
            return self

        def add_layer(self, name: str, layer, *inputs, preprocessor=None,
                      remat: bool = False):
            return self.add_vertex(
                name, LayerVertex(layer=layer, preprocessor=preprocessor,
                                  remat=remat),
                *inputs)

        def add_vertex(self, name: str, vertex: GraphVertex, *inputs):
            if name in self._conf.vertices:
                raise ValueError(f"Duplicate vertex '{name}'")
            vertex.name = name
            self._conf.vertices[name] = vertex
            self._conf.vertex_inputs[name] = list(inputs)
            self._copy_from[name] = None
            return self

        def set_outputs(self, *names):
            self._conf.network_outputs = list(names)
            return self

        def build(self) -> ComputationGraph:
            conf = self._conf
            for n in self._frozen:
                v = conf.vertices[n]
                if isinstance(v, LayerVertex):
                    v.layer = _freeze(v.layer)
            if self._ftc is not None:
                self._ftc.apply_to_conf(conf)
                for n, v in conf.vertices.items():
                    if isinstance(v, LayerVertex) and n not in self._frozen:
                        self._ftc.apply_to_layer(v.layer)
            # rebuild via GraphBuilder for topo-order + shape re-inference
            g = NeuralNetConfiguration(seed=conf.seed,
                                       updater=copy.deepcopy(conf.updater),
                                       dtype=conf.dtype)
            nb = NeuralNetConfiguration.builder()
            nb._c = g
            gb = nb.graph_builder()
            gb.add_inputs(*conf.network_inputs)
            if conf.input_types is not None:
                gb.set_input_types(*conf.input_types)
            order = topological_sort(conf.vertex_inputs, conf.network_inputs)
            for n in order:
                gb.add_vertex(n, conf.vertices[n], *conf.vertex_inputs[n])
            gb.set_outputs(*conf.network_outputs)
            if conf.backprop_type == "tbptt":
                gb.t_bptt_lengths(conf.tbptt_fwd_length,
                                  conf.tbptt_back_length)
            new_conf = gb.build()
            net = ComputationGraph(new_conf).init()
            for n, src in self._copy_from.items():
                if src is not None and n in net.params:
                    net.params[n] = jax.tree_util.tree_map(
                        lambda a: a, self._orig.params[src])
                    if src in self._orig.state:
                        net.state[n] = jax.tree_util.tree_map(
                            lambda a: a, self._orig.state[src])
            net.updater_state = new_conf.updater.init(net.params)
            return net


class TransferLearningHelper:
    """Featurization helper (reference: TransferLearningHelper.java): run the
    frozen part ONCE per dataset, then train only the unfrozen tail on the
    cached features — the frozen forward never re-executes."""

    def __init__(self, net):
        self.net = net
        if isinstance(net, MultiLayerNetwork):
            self._init_mln()
        else:
            raise ValueError("TransferLearningHelper supports "
                             "MultiLayerNetwork (use featurize + a sub-graph "
                             "manually for ComputationGraph)")

    def _init_mln(self):
        layers = self.net.conf.layers
        k = 0
        while k < len(layers) and isinstance(layers[k], FrozenLayer):
            k += 1
        if k == 0:
            raise ValueError("Network has no frozen layers")
        self._boundary = k
        # sub-network over the unfrozen tail, sharing conf hyperparams
        tail = [copy.deepcopy(l) for l in layers[k:]]
        g = NeuralNetConfiguration(seed=self.net.conf.seed,
                                   updater=copy.deepcopy(self.net.conf.updater),
                                   dtype=self.net.conf.dtype)
        nb = NeuralNetConfiguration.builder()
        nb._c = g
        # tail layers already carry their resolved nIn values, so the
        # sub-config needs no input type for re-inference
        lb = nb.list(*tail)
        sub_conf = lb.build()
        # shift preprocessors into the sub-network
        sub_conf.preprocessors = {
            i - k: p for i, p in self.net.conf.preprocessors.items()
            if i >= k}
        self.sub_net = MultiLayerNetwork(sub_conf).init(params={
            str(i - k): self.net.params[str(i)]
            for i in range(k, len(layers))})
        self.sub_net.state = {str(i - k): self.net.state.get(str(i), {})
                              for i in range(k, len(layers))}
        self.sub_net.updater_state = sub_conf.updater.init(self.sub_net.params)

    def featurize(self, ds):
        """DataSet -> DataSet with features = activations at the frozen
        boundary (reference: TransferLearningHelper.featurize)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        x = jnp.asarray(ds.features)
        mask = (jnp.asarray(ds.features_mask)
                if ds.features_mask is not None else None)
        feats, _, _, out_mask = self.net._forward(
            self.net.params, self.net.state, x, mask, train=False, rng=None,
            upto=self._boundary)
        import numpy as np
        return DataSet(np.asarray(feats), ds.labels,
                       None if out_mask is None else np.asarray(out_mask),
                       ds.labels_mask)

    def fit_featurized(self, ds, epochs: int = 1):
        """Train the unfrozen tail on featurized data, then write params back
        into the full network."""
        self.sub_net.fit(ds, epochs=epochs)
        k = self._boundary
        for i in range(k, len(self.net.conf.layers)):
            self.net.params[str(i)] = self.sub_net.params[str(i - k)]
            sub_state = self.sub_net.state.get(str(i - k), {})
            if sub_state:
                self.net.state[str(i)] = sub_state
        return self

    def output_featurized(self, features):
        return self.sub_net.output(features)

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self.sub_net
