"""Simple classification-result helpers.

Reference: nn/simple/multiclass/RankClassificationResult.java (rank class
probabilities per example, expose ranked labels) and
nn/simple/binary/BinaryClassificationResult (thresholded binary view).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class RankClassificationResult:
    """Ranked view over class probabilities [B, C] (reference:
    RankClassificationResult.java)."""

    def __init__(self, probabilities, labels: Optional[List[str]] = None):
        self.probabilities = np.asarray(probabilities)
        if self.probabilities.ndim != 2:
            raise ValueError("probabilities must be [batch, classes]")
        c = self.probabilities.shape[1]
        self.labels = (list(labels) if labels is not None
                       else [str(i) for i in range(c)])
        if len(self.labels) != c:
            raise ValueError("labels length != number of classes")
        # descending probability order per example
        self._order = np.argsort(-self.probabilities, axis=1)

    def ranked_classes(self, example: int) -> List[str]:
        """All labels for one example, best first."""
        return [self.labels[j] for j in self._order[example]]

    def max_output(self) -> List[str]:
        """Top-1 label per example."""
        return [self.labels[j] for j in self._order[:, 0]]

    def probability(self, example: int, label: str) -> float:
        return float(self.probabilities[example, self.labels.index(label)])


class BinaryClassificationResult:
    """Thresholded binary view over probabilities [B] / [B,1] / [B,2]
    (reference: nn/simple/binary/)."""

    def __init__(self, probabilities, threshold: float = 0.5):
        p = np.asarray(probabilities)
        if p.ndim == 2:
            if p.shape[1] > 2:
                raise ValueError(
                    f"binary result needs [B], [B,1] or [B,2] input, got "
                    f"{p.shape} — use RankClassificationResult for "
                    "multiclass output")
            p = p[:, 1] if p.shape[1] == 2 else p[:, 0]
        elif p.ndim != 1:
            raise ValueError(f"binary result needs [B], [B,1] or [B,2] "
                             f"input, got {p.shape}")
        self.probabilities = p
        self.threshold = float(threshold)

    def decisions(self) -> np.ndarray:
        return (self.probabilities >= self.threshold).astype(np.int64)

    def positive_count(self) -> int:
        return int(self.decisions().sum())
