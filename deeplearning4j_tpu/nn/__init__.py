"""Core NN: config system, layers, networks, updaters (reference: deeplearning4j-nn)."""
