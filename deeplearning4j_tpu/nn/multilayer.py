"""MultiLayerNetwork: the sequential-network training stack.

Reference: nn/multilayer/MultiLayerNetwork.java:82 (2909 LoC) — init/param-flattening
(:443-493), fit loop (:1047-1145), feedForward (:753), backprop (:1148,1163), TBPTT
(:1364), output (:1717-1760), rnnTimeStep streaming state.

TPU-native design: parameters are a pytree ``{layer_idx: {name: Array}}``; the whole
fit iteration — forward, loss, jax.grad backward, updater — is ONE jitted XLA program
(the reference's Solver/StochasticGradientDescent/updater call stack collapses into
it). The reference's flat-parameter-view contract (one contiguous buffer, layer
params as views) is preserved through ``params_flat()``/``set_params_flat`` for
serialization and parameter-averaging parity.

TBPTT matches MultiLayerNetwork.doTruncatedBPTT: the sequence is segmented on the
time axis, hidden state (h, c) carries across segments with stop_gradient, and each
segment is one jitted step.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers.misc import CenterLossOutputLayer
from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_key
from deeplearning4j_tpu.nn.regularization import (add_regularization_grads,
                                                  penalty_value)
from deeplearning4j_tpu.optimize.bucketing import (BoundedCache, bucket_rows,
                                                   pad_rows)
from deeplearning4j_tpu.utils.pytree import flatten_params, unflatten_params

_RNN_KEYS = ("h", "c", "kcache", "vcache", "cache_pos",
             "kpages", "vpages", "block_table",
             "kscale", "vscale", "kscales", "vscales")


def _split_state(state):
    """Split a layer-state dict into (persistent, rnn-carry) parts.

    h/c: recurrent hidden state (LSTM family). kcache/vcache/cache_pos:
    attention KV-cache streaming state (SelfAttentionLayer /
    PositionalEncodingLayer incremental decode) — present only when a
    streaming carry was seeded by rnn_time_step, never during training.
    kpages/vpages/block_table: the paged-pool variant of the same carry
    (GenerationServer's block-table serving path). kscale(s)/vscale(s):
    the per-token dequant planes riding an int8 KV-cache — carry, for
    the same reason the caches they describe are."""
    persistent, carry = {}, {}
    for k, v in state.items():
        (carry if k in _RNN_KEYS else persistent)[k] = v
    return persistent, carry


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.params: dict = {}
        self.state: dict = {}
        self.updater_state: dict = {}
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        # score_value CONTRACT: the most recent minibatch loss as an
        # array-like scalar — a device array after do_step (float() would
        # force a per-step sync and stall the dispatch pipeline), a numpy
        # scalar after a fused-fit block, float("nan") before any step. It
        # is NEVER guaranteed to be a Python float; coerce via score() (the
        # no-argument form) or float().
        self.score_value = float("nan")
        # active numerical-health policy (optimize/health.py) — set by fit()
        # for its duration (health_guard is ON by default there); do_step /
        # FusedFitDriver / CheckpointListener read it
        self._health = None
        self._base_key = None             # cached PRNGKey(seed), see _rng_base
        self._base_key_seed = None
        self._step_cache: dict = {}
        # inference/eval program cache: LRU-bounded, batch dim bucketed —
        # see optimize/bucketing.py (a serving workload with arbitrary
        # request sizes must not compile and hold a program per size)
        self._output_cache = BoundedCache()
        self._rnn_state: Optional[dict] = None  # streaming rnnTimeStep state
        self._stream_pos = 0              # tokens consumed this stream
        self._stream_capacity = None      # min attention max_cache, if any
        out = self.layers[-1] if self.layers else None
        self._has_loss_head = hasattr(out, "compute_loss_per_example")

    # ------------------------------------------------------------------ init
    def init(self, params: Optional[dict] = None) -> "MultiLayerNetwork":
        dtype = jnp.dtype(self.conf.dtype)
        rng = jax.random.PRNGKey(self.conf.seed)
        keys = jax.random.split(rng, max(len(self.layers), 1))
        if params is None:
            from deeplearning4j_tpu.utils.pytree import run_fused_on_tpu

            self.params = run_fused_on_tpu(
                lambda ks: {str(i): l.init_params(ks[i], dtype)
                            for i, l in enumerate(self.layers)}, keys)
        else:
            self.params = params
        self.state = {str(i): l.init_state(dtype) for i, l in enumerate(self.layers)}
        self.updater_state = self.conf.updater.init(self._trainable(self.params))
        return self

    def _trainable(self, params):
        return params

    # ------------------------------------------------------------- forward
    def _forward(self, params, state, x, mask, *, train, rng, carry=None,
                 upto: Optional[int] = None):
        """Run layers [0, upto). Returns (x_out, new_states, new_carry, mask_out)."""
        n = len(self.layers) if upto is None else upto
        new_states = {}
        new_carry = {}
        cur_mask = mask
        if rng is not None:
            keys = jax.random.split(rng, max(n, 1))
        for i in range(n):
            layer = self.layers[i]
            if i in self.conf.preprocessors:
                # derived, never keys[i] itself: a stochastic preprocessor
                # must not share its key with the layer behind it
                pk = preprocessor_key(keys[i]) if rng is not None else None
                x = self.conf.preprocessors[i].forward(x, rng=pk)
                cur_mask = self.conf.preprocessors[i].feed_forward_mask(cur_mask)
            layer_state = dict(state.get(str(i), {}))
            if carry is not None and str(i) in carry:
                layer_state.update(carry[str(i)])
            k = keys[i] if rng is not None else None
            x, ns = layer.forward(params[str(i)], layer_state, x, mask=cur_mask,
                                  train=train, rng=k)
            persistent, rnn_carry = _split_state(ns)
            new_states[str(i)] = persistent
            if rnn_carry:
                new_carry[str(i)] = rnn_carry
            cur_mask = layer.feed_forward_mask(cur_mask)
        return x, new_states, new_carry, cur_mask

    def feed_forward(self, x, train: bool = False):
        """All layer activations (reference: MultiLayerNetwork.feedForward :753)."""
        x = jnp.asarray(x)
        acts = [x]
        cur = x
        for i, layer in enumerate(self.layers):
            if i in self.conf.preprocessors:
                cur = self.conf.preprocessors[i].forward(cur)
            cur, _ = layer.forward(self.params[str(i)], self.state.get(str(i), {}),
                                   cur, train=train)
            acts.append(cur)
        return acts

    # --------------------------------------------------------------- loss
    def _loss(self, params, state, x, y, input_mask, label_mask, *, train, rng,
              carry=None):
        out_idx = len(self.layers) - 1
        cd = getattr(self.conf, "compute_dtype", None)
        fwd_params = params
        if cd is not None:
            # mixed precision: body layers compute in cd (bfloat16 -> MXU
            # fast path); the loss head and its params stay in the param
            # dtype. Gradients flow back through the casts to full-precision
            # leaves automatically.
            cdt = jnp.dtype(cd)
            fwd_params = {
                k: (jax.tree_util.tree_map(lambda a: a.astype(cdt), v)
                    if k != str(out_idx) else v)
                for k, v in params.items()}
            x = x.astype(cdt)
        last_in, new_states, new_carry, cur_mask = self._forward(
            fwd_params, state, x, input_mask, train=train, rng=rng,
            carry=carry, upto=out_idx)
        if cd is not None:
            last_in = last_in.astype(jnp.dtype(self.conf.dtype))
        out_layer = self.layers[out_idx]
        if out_idx in self.conf.preprocessors:
            # rng was already split inside _forward; consume only a derived
            # key here, never the parent itself
            last_in = self.conf.preprocessors[out_idx].forward(
                last_in, rng=preprocessor_key(rng))
        p_out = params[str(out_idx)]
        if isinstance(out_layer, CenterLossOutputLayer):
            per_ex = out_layer.compute_loss_per_example(
                p_out, last_in, y, state=state.get(str(out_idx)))
        else:
            per_ex = out_layer.compute_loss_per_example(p_out, last_in, y)
        lm = label_mask if label_mask is not None else cur_mask
        if lm is not None:
            lm = lm.reshape(per_ex.shape).astype(per_ex.dtype)
            data_loss = jnp.sum(per_ex * lm) / jnp.maximum(jnp.sum(lm), 1.0)
        else:
            data_loss = jnp.mean(per_ex)
        # the penalty VALUE stays in the reported score (reference:
        # computeScore adds fullNetworkL1+L2) but is not differentiated —
        # the train step adds the closed-form regularization_grad instead
        # (autodiff through these reductions measured 30% of the ResNet50
        # step, profiles/README.md); computed fused, not per-tensor
        # (per-tensor micro-reductions measured 43% of the bf16 step)
        reg = penalty_value(self, params)
        if not isinstance(reg, float):
            reg = jax.lax.stop_gradient(reg)
        new_states[str(out_idx)] = state.get(str(out_idx), {})
        return data_loss + reg, (new_states, new_carry, last_in)

    # ---------------------------------------------------------- train step
    def _lr_mult_tree(self):
        """Per-leaf learning-rate multiplier pytree (structure == params), honoring
        per-layer ``learning_rate`` and ``bias_learning_rate`` overrides (reference:
        BaseMultiLayerUpdater per-param LR resolution). Returns None when every
        multiplier is 1 (the common case — keeps the update one fused tree_map)."""
        base_lr = getattr(self.conf.updater, "learning_rate", None)
        if not base_lr:
            return None
        any_override = False
        tree: dict = {}
        for i, layer in enumerate(self.layers):
            layer_lr = getattr(layer, "learning_rate", None)
            bias_lr = getattr(layer, "bias_learning_rate", None)
            biases = (layer.bias_param_names()
                      if hasattr(layer, "bias_param_names") else frozenset())
            leaf = {}
            for name in self.params.get(str(i), {}):
                lr = bias_lr if (name in biases and bias_lr is not None) else layer_lr
                leaf[name] = (lr / base_lr) if lr is not None else 1.0
                if lr is not None:
                    any_override = True
            tree[str(i)] = leaf
        return tree if any_override else None

    def _rng_base(self):
        """Cached base PRNG key — rebuilt only when conf.seed changes. The
        per-step key is fold_in(base, iteration); reconstructing PRNGKey
        (an XLA dispatch) every do_step was pure per-iteration overhead."""
        if self._base_key is None or self._base_key_seed != self.conf.seed:
            self._base_key = jax.random.PRNGKey(self.conf.seed)
            self._base_key_seed = self.conf.seed
        return self._base_key

    def _make_step(self, with_carry: bool, guarded: bool = False):
        from deeplearning4j_tpu.optimize.fused_fit import build_step_core

        # the step body (forward/loss/grad/regularization/normalization/
        # updater/center-update) is the SHARED core also scanned by the
        # fused K-step driver and ParallelWrapper's device round
        core = build_step_core(self, guarded=guarded)

        def step(params, opt_state, state, rng, iteration, x, y, input_mask,
                 label_mask, carry):
            return core(params, opt_state, state, rng, iteration, x, y,
                        input_mask, label_mask,
                        carry if with_carry else None)

        # params/opt/state buffers are dead after the call (do_step rebinds
        # them from the outputs) — donation lets XLA update in place instead
        # of allocating a second copy of the model (VERDICT r2: trains held
        # 2x param memory for no reason)
        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _get_step(self, key):
        if key not in self._step_cache:
            if key[0] == "fused":
                from deeplearning4j_tpu.optimize.fused_fit import \
                    build_fused_step
                self._step_cache[key] = build_fused_step(self,
                                                         guarded=key[-1])
            else:
                self._step_cache[key] = self._make_step(with_carry=key[-2],
                                                        guarded=key[-1])
        return self._step_cache[key]

    def do_step(self, x, y, input_mask=None, label_mask=None, carry=None):
        """One SGD iteration on one minibatch; returns the minibatch loss."""
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        input_mask = jnp.asarray(input_mask) if input_mask is not None else None
        label_mask = jnp.asarray(label_mask) if label_mask is not None else None
        with_carry = carry is not None
        health = self._health
        guarded = health is not None
        key = (x.shape, y.shape, input_mask is not None, label_mask is not None,
               with_carry, guarded)
        step = self._get_step(key)
        rng = jax.random.fold_in(self._rng_base(), self.iteration)
        out = step(
            self.params, self.updater_state, self.state, rng,
            jnp.asarray(self.iteration, jnp.float32), x, y, input_mask, label_mask,
            carry if with_carry else {})
        if guarded:
            (self.params, self.updater_state, self.state, new_carry, loss,
             skip) = out
        else:
            self.params, self.updater_state, self.state, new_carry, loss = out
        self.iteration += 1
        # score_value stays a device scalar: float() would force a sync every
        # step and stall the dispatch pipeline; it coerces on first use
        self.score_value = loss
        it_done = self.iteration
        if guarded:
            # observe BEFORE listener dispatch: health-gated checkpoint
            # listeners (elastic.CheckpointListener) must see THIS step's
            # skip state, and a recovery/raise precedes the listener round
            score_h, skip_h = jax.device_get((loss, skip))
            health.observe(self, score_h, skip_h, it_done - 1)
        for listener in self.listeners:
            listener.iteration_done(self, it_done)
        return self.score_value, new_carry

    # ------------------------------------------------------------------ fit
    def fit(self, data, labels=None, epochs: int = 1, *,
            fused_steps: Optional[int] = None, prefetch_depth: int = 2,
            health_guard=True):
        """Train. ``data`` may be (features, labels) arrays, a DataSet, or a
        DataSetIterator (reference: MultiLayerNetwork.fit :1047).

        The default fast path fuses ``fused_steps`` minibatches (default
        ``optimize.fused_fit.DEFAULT_FUSED_STEPS``) into one jitted
        ``lax.scan`` block fed by device-prefetched input — pass
        ``fused_steps=1`` to opt out and run one jitted program per
        minibatch. TBPTT always runs unfused. Listeners still fire per
        iteration but scores materialize per block (one device fetch per
        ``fused_steps`` iterations); listener hooks observe end-of-block
        parameters.

        ``health_guard`` (default ON) fuses the numerical-health guard into
        the step: a non-finite loss/gradient microbatch is skipped on
        device (identity update) and a host-side recovery ladder handles
        divergence — LR backoff, then rollback to the last healthy-gated
        checkpoint (when the policy has a store), then ``DivergenceError``.
        Pass ``None``/``False`` to opt out, or an
        ``optimize.health.HealthPolicy`` to configure thresholds and attach
        an ``elastic.CheckpointStore``. Recovery events fire
        ``on_health(model, report)`` on attached listeners."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.optimize.fused_fit import (FusedFitDriver,
                                                           resolve_fused_steps)
        from deeplearning4j_tpu.optimize.health import resolve_health_policy

        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        K = resolve_fused_steps(self, fused_steps)
        policy = resolve_health_policy(health_guard)
        prev_health = self._health
        if policy is not None:
            policy.bind(self)
        self._health = policy
        try:
            if isinstance(data, DataSet):
                if K > 1 and epochs > 1:
                    # repeated single-batch fit: the epochs loop IS the
                    # stream — fuse it (the DataSet path fires no epoch
                    # listeners, so semantics are unchanged)
                    FusedFitDriver(self, K, prefetch_depth).fit_stream(
                        data for _ in range(epochs))
                    return self
                for _ in range(epochs):
                    self._fit_batch(data)
                return self
            driver = (FusedFitDriver(self, K, prefetch_depth)
                      if K > 1 else None)
            for _ in range(epochs):
                for listener in self.listeners:
                    listener.on_epoch_start(self)
                if hasattr(data, "reset"):
                    data.reset()
                if driver is not None:
                    driver.fit_stream(iter(data))
                else:
                    for ds in data:
                        self._fit_batch(ds)
                for listener in self.listeners:
                    listener.on_epoch_end(self)
                self.epoch += 1
            return self
        finally:
            self._health = prev_health

    def _fit_batch(self, ds):
        if self.conf.backprop_type == "tbptt" and ds.features.ndim == 3:
            self._fit_tbptt(ds)
        else:
            self.do_step(ds.features, ds.labels, ds.features_mask, ds.labels_mask)

    def _fit_tbptt(self, ds):
        """Truncated BPTT (reference: MultiLayerNetwork.java:1364 doTruncatedBPTT)."""
        T = ds.features.shape[1]
        L = self.conf.tbptt_fwd_length
        n_seg = max(1, math.ceil(T / L))
        carry: dict = {}
        for s in range(n_seg):
            sl = slice(s * L, min((s + 1) * L, T))
            fx = ds.features[:, sl]
            fy = ds.labels[:, sl] if ds.labels.ndim == 3 else ds.labels
            fm = ds.features_mask[:, sl] if ds.features_mask is not None else None
            lm = ds.labels_mask[:, sl] if ds.labels_mask is not None else None
            _, carry = self.do_step(fx, fy, fm, lm, carry=carry)
            carry = jax.tree_util.tree_map(jax.lax.stop_gradient, carry)

    # ------------------------------------------------------------- inference
    def _get_output(self, key, build):
        """Bounded cache for the inference/eval program family (forward,
        rnn-stream, fused-eval). One hook point, so the test suite's
        recompile guard can count cache misses per network instance."""
        if key not in self._output_cache:
            self._output_cache[key] = build()
        return self._output_cache[key]

    def output(self, x, train: bool = False, mask=None):
        """Final-layer activations (reference: MultiLayerNetwork.output :1717,
        incl. the mask-array overload — masks flow through the layers so e.g.
        LastTimeStep / masked global pooling are correct for padded batches).

        The batch dim is BUCKETED (padded to the next power of two by
        replicating the last row, stripped from the result) so the jit cache
        holds O(log max_batch) programs instead of one per request size."""
        x = jnp.asarray(x)
        mask = jnp.asarray(mask) if mask is not None else None
        n = x.shape[0]
        B = bucket_rows(n)
        if B != n:
            x = pad_rows(x, B)
            if mask is not None:
                mask = pad_rows(mask, B)
        key = (x.shape, train, mask is not None)

        def build():
            def fwd(params, state, xx, mm):
                out, _, _, _ = self._forward(params, state, xx, mm,
                                             train=train, rng=None)
                return out
            return jax.jit(fwd)

        out = self._get_output(key, build)(self.params, self.state, x, mask)
        return out if B == n else out[:n]

    def score(self, ds=None, x=None, y=None) -> float:
        """Loss (incl. regularization) on a dataset, as a Python float
        (reference: computeGradientAndScore). With NO arguments, coerces and
        returns the last training minibatch's loss — the float view of the
        ``score_value`` contract (score_value itself stays device-side)."""
        if ds is None and x is None:
            return float(self.score_value)
        if ds is not None:
            x, y = ds.features, ds.labels
            im, lm = ds.features_mask, ds.labels_mask
        else:
            im = lm = None
        loss, _ = self._loss(self.params, self.state, jnp.asarray(x), jnp.asarray(y),
                             None if im is None else jnp.asarray(im),
                             None if lm is None else jnp.asarray(lm),
                             train=False, rng=None)
        return float(loss)

    def evaluate(self, data, labels=None, *, top_n: int = 1, fused=None,
                 eval_batches: Optional[int] = None, prefetch_depth: int = 2):
        """Classification evaluation (reference: MultiLayerNetwork.evaluate).

        The default fast path is the device-resident fused evaluator
        (evaluation/fused_eval.py): forward + argmax + masked scatter-add
        into a donated device accumulator, ``eval_batches`` batches per
        dispatch, ONE small fetch per call instead of per-batch logit
        transfers. Pass ``fused=False`` to opt out (per-batch ``output()``
        + host numpy counting)."""
        from deeplearning4j_tpu.evaluation.classification import Evaluation
        from deeplearning4j_tpu.datasets.dataset import DataSet

        ev = Evaluation(top_n=top_n)
        if labels is not None:
            data = [DataSet(np.asarray(data), np.asarray(labels))]
        elif isinstance(data, DataSet):
            data = [data]
        elif hasattr(data, "reset"):
            data.reset()
        if fused is None or fused:
            from deeplearning4j_tpu.evaluation.fused_eval import \
                FusedEvalDriver
            return FusedEvalDriver(self, eval_batches,
                                   prefetch_depth).evaluate(data, ev)
        for ds in data:
            out = self.output(ds.features, mask=ds.features_mask)
            ev.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
        return ev

    # ------------------------------------------------------- rnn streaming
    def rnn_clear_previous_state(self):
        self._rnn_state = None
        self._stream_pos = 0
        self._stream_capacity = None

    def rnn_time_step(self, x):
        """Streaming single/multi-step inference with persistent state (reference:
        MultiLayerNetwork.rnnTimeStep)."""
        x = jnp.asarray(x)
        squeeze = False
        if x.ndim == 2:  # [B, F] -> single timestep
            x = x[:, None, :]
            squeeze = True
        if self._rnn_state is None:
            # fresh stream: layers that stream through explicit caches
            # (attention KV caches) seed their carry here; LSTMs need
            # nothing (h/c default lazily to zeros)
            self._rnn_state = self._seed_streaming_carry(x.shape[0])
        # overflow must be caught HERE (static position accounting): the
        # jitted step's cache_pos is a tracer, and dynamic_update_slice
        # would silently clamp and corrupt the cache tail
        if self._stream_capacity is not None and \
                self._stream_pos + x.shape[1] > self._stream_capacity:
            raise ValueError(
                f"KV cache overflow: stream position {self._stream_pos} + "
                f"{x.shape[1]} new tokens > max_cache "
                f"{self._stream_capacity}; raise SelfAttentionLayer."
                "max_cache or rnn_clear_previous_state()")
        self._stream_pos += x.shape[1]
        carry = self._rnn_state or {}
        # jitted per (shape, carry structure) — see ComputationGraph
        # .rnn_time_step: eager per-op dispatch dominates streaming cost
        key = ("rnn_stream", x.shape, jax.tree_util.tree_structure(carry))

        def build():
            def fwd(params, state, x, carry):
                out, _, new_carry, _ = self._forward(
                    params, state, x, None, train=False, rng=None,
                    carry=carry)
                return out, new_carry
            return jax.jit(fwd)

        out, new_carry = self._get_output(key, build)(self.params, self.state,
                                                      x, carry)
        self._rnn_state = new_carry
        return out[:, 0] if squeeze and out.ndim == 3 else out

    def _stream_layers(self):
        """(name, layer) pairs keyed exactly as the streaming carry dict —
        the shared vocabulary between ``_seed_streaming_carry`` and
        carry-restructuring callers (GenerationServer's paged pool)."""
        for i, layer in enumerate(self.layers):
            yield str(i), layer

    def _seed_streaming_carry(self, batch: int) -> dict:
        """Initial streaming carry + resets static overflow accounting."""
        dtype = jnp.dtype(self.conf.dtype)
        seed = {}
        caps = []
        for name, layer in self._stream_layers():
            c = layer.init_streaming_carry(batch, dtype)
            if c:
                seed[name] = c
                if hasattr(layer, "max_cache"):
                    caps.append(layer.max_cache)
        self._stream_pos = 0
        self._stream_capacity = min(caps) if caps else None
        return seed

    # ---------------------------------------------------------- pretraining
    def pretrain(self, data_iterator, epochs: int = 1):
        """Layerwise unsupervised pretraining for VAE/AutoEncoder layers
        (reference: MultiLayerNetwork.pretrain)."""
        for i, layer in enumerate(self.layers):
            if not hasattr(layer, "pretrain_loss_per_example") and \
               not hasattr(layer, "reconstruction_loss_per_example"):
                continue
            self._pretrain_layer(i, data_iterator, epochs)
        return self

    def _pretrain_layer(self, idx, data_iterator, epochs):
        layer = self.layers[idx]
        updater = self.conf.updater
        opt_state = updater.init({str(idx): self.params[str(idx)]})

        @jax.jit
        def pstep(p_layer, opt_state, all_params, rng, iteration, x):
            # three independent keys: lower-stack forward (so stochastic
            # preprocessors BELOW idx resample fresh every step instead of
            # freezing on their rng=None fallback), this layer's input
            # preprocessor, and the pretrain loss itself
            k_fwd, k_prep, k_loss = jax.random.split(rng, 3)
            feats, _, _, _ = self._forward(all_params, self.state, x, None,
                                           train=False, rng=k_fwd, upto=idx)
            if idx in self.conf.preprocessors:
                feats = self.conf.preprocessors[idx].forward(feats,
                                                             rng=k_prep)

            def loss_fn(pl):
                if hasattr(layer, "pretrain_loss_per_example"):
                    per = layer.pretrain_loss_per_example(pl[str(idx)], feats,
                                                          k_loss)
                else:
                    per = layer.reconstruction_loss_per_example(
                        pl[str(idx)], feats, k_loss)
                return jnp.mean(per)

            loss, grads = jax.value_and_grad(loss_fn)(p_layer)
            # regularization.py invariant: every jax.grad consumer adds the
            # closed-form l1/l2 gradient (DL4J's BaseUpdater.postApply
            # applies decay during layerwise pretraining too); layers
            # outside p_layer contribute nothing
            grads = add_regularization_grads(self, p_layer, grads)
            steps, new_opt = updater.step(grads, opt_state, iteration)
            new_p = jax.tree_util.tree_map(lambda p, s: p - s, p_layer, steps)
            return new_p, new_opt, loss

        it = 0
        for _ in range(epochs):
            if hasattr(data_iterator, "reset"):
                data_iterator.reset()
            iterable = (data_iterator if not hasattr(data_iterator, "features")
                        else [data_iterator])
            for ds in iterable:
                rng = jax.random.fold_in(jax.random.PRNGKey(self.conf.seed + idx), it)
                p_layer = {str(idx): self.params[str(idx)]}
                p_layer, opt_state, loss = pstep(
                    p_layer, opt_state, self.params, rng,
                    jnp.asarray(it, jnp.float32), jnp.asarray(ds.features))
                self.params[str(idx)] = p_layer[str(idx)]
                it += 1

    # ------------------------------------------------------- params plumbing
    def params_flat(self) -> np.ndarray:
        """One contiguous parameter vector (reference: MultiLayerNetwork.params() /
        flattenedParams, :103,443-493). Order: layer index, then param_order."""
        return flatten_params(self.params, self.layers)

    def set_params_flat(self, flat) -> None:
        self.params = unflatten_params(flat, self.params, self.layers)

    def num_params(self) -> int:
        return int(sum(np.prod(v.shape) for lp in self.params.values()
                       for v in lp.values()))

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    def clone(self) -> "MultiLayerNetwork":
        import copy
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        net.init()
        # leaf .copy(): the train step donates its input buffers, so a
        # reference-sharing clone would be invalidated by further training
        net.params = jax.tree_util.tree_map(lambda a: a.copy(), self.params)
        net.state = jax.tree_util.tree_map(lambda a: a.copy(), self.state)
        net.updater_state = jax.tree_util.tree_map(lambda a: a.copy(),
                                           self.updater_state)
        net.iteration = self.iteration
        return net
