"""InputType: shape metadata used for layer nIn inference + preprocessor insertion.

Reference: nn/conf/inputs/InputType.java (FF / recurrent / convolutional /
convolutionalFlat kinds). TPU-first divergence: image arrays are NHWC (the layout
XLA:TPU prefers) and recurrent arrays are [batch, time, features] — the reference
uses NCHW and [batch, features, time]. The *logical* config fields (height, width,
depth/channels, size) keep the reference's meaning.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclass(frozen=True)
class InputType:
    kind: str = "feed_forward"  # feed_forward | recurrent | convolutional | convolutional_flat
    size: int = 0               # FF/recurrent feature count
    timeseries_length: Optional[int] = None
    height: int = 0
    width: int = 0
    channels: int = 0

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="feed_forward", size=int(size))

    @staticmethod
    def recurrent(size: int, timeseries_length: Optional[int] = None) -> "InputType":
        return InputType(kind="recurrent", size=int(size),
                         timeseries_length=timeseries_length)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional", height=int(height), width=int(width),
                         channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional_flat", height=int(height),
                         width=int(width), channels=int(channels),
                         size=int(height) * int(width) * int(channels))

    def flat_size(self) -> int:
        if self.kind in ("feed_forward", "recurrent"):
            return self.size
        return self.height * self.width * self.channels

    def array_shape(self, batch: int = -1):
        """Concrete array shape (batch dim first; NHWC / [B,T,F] layouts)."""
        if self.kind == "feed_forward" or self.kind == "convolutional_flat":
            return (batch, self.flat_size())
        if self.kind == "recurrent":
            t = self.timeseries_length if self.timeseries_length else -1
            return (batch, t, self.size)
        return (batch, self.height, self.width, self.channels)
