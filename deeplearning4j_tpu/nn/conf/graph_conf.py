"""ComputationGraph configuration: vertices + GraphBuilder.

Reference: nn/conf/ComputationGraphConfiguration.java:438 (GraphBuilder) and the
vertex conf/impl pairs under nn/conf/graph/ + nn/graph/vertex/impl/ (MergeVertex,
ElementWiseVertex, StackVertex, UnstackVertex, SubsetVertex, ScaleVertex,
ShiftVertex, L2Vertex, L2NormalizeVertex, ReshapeVertex, PoolHelperVertex,
PreprocessorVertex, rnn/LastTimeStepVertex, rnn/DuplicateToTimeSeriesVertex).

TPU-native design: a vertex is a *pure function* over its input arrays — the
reference's per-vertex doForward/doBackward pairs collapse to forward-only
functions differentiated by jax.grad, and the whole DAG (in topological order)
traces into ONE XLA program. The graph structure itself lives in the config
(names, input lists, topo order computed at build with Kahn + cycle detection,
mirroring ComputationGraph.java:1084-1186) so the runtime never re-derives it.

Layouts are TPU-first: NHWC images, [B, T, F] sequences — so "the feature axis"
is always the last axis, which makes Merge/Subset single-axis ops that XLA fuses
into neighbouring work.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.builders import (
    NeuralNetConfiguration,
    default_preprocessor,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.preprocessors import (
    InputPreProcessor,
    preprocessor_key,
)
from deeplearning4j_tpu.nn.updater import Sgd, Updater
from deeplearning4j_tpu.utils import serde
from deeplearning4j_tpu.utils.serde import register_serializable


# --------------------------------------------------------------------------- #
# Vertex contract
# --------------------------------------------------------------------------- #
@dataclass
class GraphVertex:
    """Base vertex: a pure function of its input arrays.

    Contract (multi-input analogue of the Layer contract in layers/base.py):

    - ``init_params(rng, dtype) -> dict``; ``param_order() -> [names]``
    - ``forward(params, state, inputs, *, masks, ctx, train, rng)``
      -> ``(out, new_state)`` where ``inputs``/``masks`` are lists parallel to
      the vertex's declared inputs and ``ctx`` carries network-input arrays and
      masks for vertices that need them (LastTimeStepVertex mask lookup,
      DuplicateToTimeSeriesVertex length lookup).
    - ``output_type(input_types) -> InputType`` for shape inference.
    """

    name: Optional[str] = None

    def finalize(self, g=None) -> None:
        pass

    def param_order(self) -> list:
        return []

    def init_params(self, rng, dtype=jnp.float32) -> dict:
        return {}

    def init_state(self, dtype=jnp.float32) -> dict:
        return {}

    def has_params(self) -> bool:
        return bool(self.param_order())

    def regularization(self, params):
        return 0.0

    def regularization_grad(self, params):
        return {}

    def output_type(self, input_types: list) -> InputType:
        return input_types[0]

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        raise NotImplementedError

    def feed_forward_mask(self, masks):
        """Combine/propagate input time-masks (default: first non-None)."""
        if not masks:
            return None
        for m in masks:
            if m is not None:
                return m
        return None


@register_serializable
@dataclass
class LayerVertex(GraphVertex):
    """A Layer inside the graph, with an optional InputPreProcessor
    (reference: nn/graph/vertex/impl/LayerVertex.java)."""

    layer: Optional[Layer] = None
    preprocessor: Optional[InputPreProcessor] = None
    # rematerialization (jax.checkpoint): when True, this vertex's
    # INTERNAL activations are recomputed in the backward pass instead of
    # stored — trading MXU FLOPs for HBM. Per-vertex boundary: the
    # vertex's OUTPUT is still a residual for downstream consumers, so
    # the win is the intermediates inside the vertex (attention scores /
    # pre-activations), not whole-block activation memory. Training-path
    # only; inference/streaming never stashes.
    remat: bool = False

    def finalize(self, g=None) -> None:
        self.layer.finalize(g)

    def param_order(self):
        return self.layer.param_order()

    def init_params(self, rng, dtype=jnp.float32):
        return self.layer.init_params(rng, dtype)

    def init_state(self, dtype=jnp.float32):
        return self.layer.init_state(dtype)

    def regularization(self, params):
        return self.layer.regularization(params)

    def regularization_grad(self, params):
        return self.layer.regularization_grad(params)

    def output_type(self, input_types):
        it = input_types[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.output_type(it)

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if self.preprocessor is not None:
            x = self.preprocessor.forward(x, rng=preprocessor_key(rng))
            mask = self.preprocessor.feed_forward_mask(mask)
        if self.remat and train:
            import jax as _jax

            def run(p, xx, st, mk, k):
                return self.layer.forward(p, st, xx, mask=mk, train=True,
                                          rng=k)

            return _jax.checkpoint(run)(params, x, state, mask, rng)
        return self.layer.forward(params, state, x, mask=mask, train=train,
                                  rng=rng)

    def feed_forward_mask(self, masks):
        mask = masks[0] if masks else None
        if self.preprocessor is not None:
            mask = self.preprocessor.feed_forward_mask(mask)
        return self.layer.feed_forward_mask(mask)


@register_serializable
@dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature axis — last axis in our NHWC/[B,T,F]
    layouts (reference: nn/graph/vertex/impl/MergeVertex.java, which merges
    along dim 1 in NCHW; same logical op)."""

    def output_type(self, input_types):
        first = input_types[0]
        if first.kind == "convolutional":
            return InputType.convolutional(
                first.height, first.width,
                sum(t.channels for t in input_types))
        if first.kind == "recurrent":
            return InputType.recurrent(sum(t.size for t in input_types),
                                       first.timeseries_length)
        return InputType.feed_forward(sum(t.flat_size() for t in input_types))

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        return jnp.concatenate(inputs, axis=-1), state


@register_serializable
@dataclass
class ElementWiseVertex(GraphVertex):
    """Pointwise combine: Add | Subtract | Product | Average | Max
    (reference: nn/conf/graph/ElementWiseVertex.java Op enum)."""

    op: str = "add"

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract requires exactly 2 inputs")
            out = inputs[0] - inputs[1]
        elif op == "product":
            for x in inputs[1:]:
                out = out * x
        elif op == "average":
            out = sum(inputs) / float(len(inputs))
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"Unknown ElementWiseVertex op '{self.op}'")
        return out, state


@register_serializable
@dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive (reference:
    nn/graph/vertex/impl/SubsetVertex.java)."""

    from_index: int = 0
    to_index: int = 0

    def output_type(self, input_types):
        n = self.to_index - self.from_index + 1
        it = input_types[0]
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timeseries_length)
        if it.kind == "convolutional":
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feed_forward(n)

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        return inputs[0][..., self.from_index:self.to_index + 1], state


@register_serializable
@dataclass
class StackVertex(GraphVertex):
    """Concatenate along the batch (0) axis (reference:
    nn/graph/vertex/impl/StackVertex.java)."""

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        return jnp.concatenate(inputs, axis=0), state


@register_serializable
@dataclass
class UnstackVertex(GraphVertex):
    """Select slice ``from_index`` of ``stack_size`` equal batch chunks
    (reference: nn/graph/vertex/impl/UnstackVertex.java)."""

    from_index: int = 0
    stack_size: int = 1

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step], state


@register_serializable
@dataclass
class ScaleVertex(GraphVertex):
    """out = scale * x (reference: nn/conf/graph/ScaleVertex.java)."""

    scale: float = 1.0

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        return inputs[0] * self.scale, state


@register_serializable
@dataclass
class ShiftVertex(GraphVertex):
    """out = x + shift (reference: nn/conf/graph/ShiftVertex.java)."""

    shift: float = 0.0

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        return inputs[0] + self.shift, state


@register_serializable
@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs -> [B, 1] (reference:
    nn/graph/vertex/impl/L2Vertex.java; eps guards the sqrt gradient at 0)."""

    eps: float = 1e-8

    def output_type(self, input_types):
        return InputType.feed_forward(1)

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        a, b = inputs[0], inputs[1]
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps), state


@register_serializable
@dataclass
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over all non-batch axes (reference:
    nn/graph/vertex/impl/L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        x = inputs[0]
        flat = x.reshape(x.shape[0], -1)
        norm = jnp.sqrt(jnp.sum(flat * flat, axis=1) + self.eps)
        return x / norm.reshape((-1,) + (1,) * (x.ndim - 1)), state


@register_serializable
@dataclass
class PreprocessorVertex(GraphVertex):
    """Standalone InputPreProcessor as a vertex (reference:
    nn/graph/vertex/impl/PreprocessorVertex.java)."""

    preprocessor: Optional[InputPreProcessor] = None

    def output_type(self, input_types):
        return self.preprocessor.output_type(input_types[0])

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        return (self.preprocessor.forward(inputs[0],
                                          rng=preprocessor_key(rng)),
                state)

    def feed_forward_mask(self, masks):
        return self.preprocessor.feed_forward_mask(masks[0] if masks else None)


@register_serializable
@dataclass
class ReshapeVertex(GraphVertex):
    """Reshape non-batch dims (reference: nn/graph/vertex/impl/ReshapeVertex.java)."""

    shape: tuple = ()

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape)), state


@register_serializable
@dataclass
class PoolHelperVertex(GraphVertex):
    """Strip the first row+column of CNN activations — compatibility shim for
    imported GoogLeNet-style models (reference:
    nn/graph/vertex/impl/PoolHelperVertex.java). NHWC here."""

    def output_type(self, input_types):
        it = input_types[0]
        return InputType.convolutional(it.height - 1, it.width - 1, it.channels)

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        return inputs[0][:, 1:, 1:, :], state


@register_serializable
@dataclass
class LastTimeStepVertex(GraphVertex):
    """[B,T,F] -> [B,F] at the last *active* timestep per the mask of the named
    network input (reference: nn/graph/vertex/impl/rnn/LastTimeStepVertex.java)."""

    mask_input: Optional[str] = None

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        x = inputs[0]
        mask = None
        if self.mask_input is not None and ctx is not None:
            mask = ctx.get("input_masks", {}).get(self.mask_input)
        if mask is None and masks:
            mask = masks[0]
        if mask is None:
            return x[:, -1, :], state
        T = x.shape[1]
        m = mask.astype(jnp.float32)
        # index of last nonzero mask entry (handles non-contiguous masks)
        idx = jnp.argmax(jnp.arange(1, T + 1, dtype=jnp.float32)[None, :] * m,
                         axis=1).astype(jnp.int32)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :], state

    def feed_forward_mask(self, masks):
        return None


@register_serializable
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B,F] -> [B,T,F], T taken from the named network input (reference:
    nn/graph/vertex/impl/rnn/DuplicateToTimeSeriesVertex.java)."""

    input_name: Optional[str] = None

    def output_type(self, input_types):
        return InputType.recurrent(input_types[0].flat_size())

    def forward(self, params, state, inputs, *, masks=None, ctx=None,
                train=False, rng=None):
        x = inputs[0]
        ref = ctx["input_arrays"][self.input_name]
        T = ref.shape[1]
        out = jnp.broadcast_to(x[:, None, :], (x.shape[0], T, x.shape[1]))
        if self.input_name is not None and ctx is not None:
            m = ctx.get("input_masks", {}).get(self.input_name)
            if m is not None:
                return out, state
        return out, state

    def feed_forward_mask(self, masks):
        return None


# --------------------------------------------------------------------------- #
# Configuration + builder
# --------------------------------------------------------------------------- #
@register_serializable
@dataclass
class ComputationGraphConfiguration:
    """Finalised DAG config (reference: nn/conf/ComputationGraphConfiguration.java).

    ``topo_order`` is computed once at build (Kahn + cycle detection, parity with
    ComputationGraph.java:1084-1186) and serialized, so restores skip re-sorting.
    """

    network_inputs: list = field(default_factory=list)
    network_outputs: list = field(default_factory=list)
    vertices: dict = field(default_factory=dict)        # {name: GraphVertex}
    vertex_inputs: dict = field(default_factory=dict)   # {name: [input names]}
    topo_order: list = field(default_factory=list)
    input_types: Optional[list] = None
    seed: int = 0
    updater: Updater = field(default_factory=lambda: Sgd(learning_rate=0.1))
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    pretrain: bool = False
    dtype: str = "float32"
    compute_dtype: Optional[str] = None

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return serde.from_json(s)

    def n_layers(self) -> int:
        return sum(1 for v in self.vertices.values()
                   if isinstance(v, LayerVertex))


def topological_sort(vertex_inputs: dict, network_inputs: list) -> list:
    """Kahn's algorithm over vertex names; raises on cycles or dangling inputs
    (reference: ComputationGraph.java:1084-1186)."""
    names = list(vertex_inputs.keys())
    known = set(names) | set(network_inputs)
    for name, ins in vertex_inputs.items():
        for i in ins:
            if i not in known:
                raise ValueError(f"Vertex '{name}' input '{i}' is not a network "
                                 "input or another vertex")
    indeg = {n: sum(1 for i in vertex_inputs[n] if i not in network_inputs)
             for n in names}
    children: dict = {n: [] for n in names}
    for name, ins in vertex_inputs.items():
        for i in ins:
            if i in children:
                children[i].append(name)
    queue = [n for n in names if indeg[n] == 0]
    order = []
    while queue:
        n = queue.pop(0)
        order.append(n)
        for c in children[n]:
            indeg[c] -= 1
            if indeg[c] == 0:
                queue.append(c)
    if len(order) != len(names):
        cyc = [n for n in names if n not in order]
        raise ValueError(f"Cycle detected in graph at vertices {cyc}")
    return order


class GraphBuilder:
    """Fluent DAG builder (reference: ComputationGraphConfiguration.GraphBuilder,
    nn/conf/ComputationGraphConfiguration.java:438)."""

    def __init__(self, global_conf: NeuralNetConfiguration):
        self._g = global_conf
        self._inputs: list = []
        self._outputs: list = []
        self._vertices: dict = {}
        self._vertex_inputs: dict = {}
        self._input_types: Optional[list] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._pretrain = False

    def add_inputs(self, *names) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs,
                  preprocessor: Optional[InputPreProcessor] = None,
                  remat: bool = False) -> "GraphBuilder":
        return self.add_vertex(
            name, LayerVertex(layer=layer, preprocessor=preprocessor,
                              remat=remat), *inputs)

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs
                   ) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"Duplicate vertex/input name '{name}'")
        if not inputs:
            raise ValueError(f"Vertex '{name}' needs at least one input")
        vertex.name = name
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop_type(self, t: str, fwd_length: int = 20, back_length: int = 20
                      ) -> "GraphBuilder":
        self._backprop_type = t
        self._tbptt_fwd = fwd_length
        self._tbptt_back = back_length
        return self

    def t_bptt_lengths(self, fwd: int, back: Optional[int] = None
                       ) -> "GraphBuilder":
        return self.backprop_type("tbptt", fwd,
                                  back if back is not None else fwd)

    def pretrain(self, flag: bool) -> "GraphBuilder":
        self._pretrain = flag
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs:
            raise ValueError("Graph has no inputs (call add_inputs)")
        if not self._outputs:
            raise ValueError("Graph has no outputs (call set_outputs)")
        for o in self._outputs:
            if o not in self._vertices:
                raise ValueError(f"Output '{o}' is not a vertex")
        vertices = {k: copy.deepcopy(v) for k, v in self._vertices.items()}
        vertex_inputs = {k: list(v) for k, v in self._vertex_inputs.items()}
        order = topological_sort(vertex_inputs, self._inputs)

        # shape inference + preprocessor auto-insertion + nIn setting, in topo
        # order (parity with the reference's addPreProcessors + setNIn pass)
        types: dict = {}
        if self._input_types is not None:
            if len(self._input_types) != len(self._inputs):
                raise ValueError("set_input_types arity != add_inputs arity")
            types.update(dict(zip(self._inputs, self._input_types)))
        for name in order:
            v = vertices[name]
            v.finalize(self._g)
            in_types = [types.get(i) for i in vertex_inputs[name]]
            if any(t is None for t in in_types):
                # no input types declared: shape inference is impossible,
                # but config sanity (n_in/n_out, conv geometry) must still
                # run — the MLN path validates unconditionally
                if isinstance(v, LayerVertex):
                    v.layer.validate()
                continue
            if isinstance(v, LayerVertex):
                it = in_types[0]
                if v.preprocessor is None:
                    v.preprocessor = default_preprocessor(it, v.layer)
                if v.preprocessor is not None:
                    it = v.preprocessor.output_type(it)
                v.layer.set_n_in(it)
                v.layer.validate()
                types[name] = v.layer.output_type(it)
            else:
                types[name] = v.output_type(in_types)

        return ComputationGraphConfiguration(
            network_inputs=list(self._inputs),
            network_outputs=list(self._outputs),
            vertices=vertices,
            vertex_inputs=vertex_inputs,
            topo_order=order,
            input_types=self._input_types,
            seed=self._g.seed,
            updater=copy.deepcopy(self._g.updater),
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            pretrain=self._pretrain,
            dtype=self._g.dtype,
            compute_dtype=self._g.compute_dtype,
        )
