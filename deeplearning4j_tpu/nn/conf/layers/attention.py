"""Attention layers.

The 0.8.x reference has no attention (SURVEY §5: long-context = TBPTT only);
later DL4J releases added SelfAttentionLayer/RecurrentAttentionLayer — these
provide that capability, TPU-first: one fused softmax(QK^T/sqrt(d))V program
whose matmuls are MXU-shaped [B*H, T, d], with optional causal masking and
time-mask support. The sequence-parallel (ring) execution of the same math
lives in parallel/sequence.py.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayer, Layer
from deeplearning4j_tpu.utils.serde import register_serializable

NEG_INF = -1e30


def _debug_paged_overflow(pos, T, NP, ps):
    """Debug-mode paged-capacity assert (DL4J_TPU_PAGED_DEBUG=1). In
    production the check lives in the CALLER's page-accounting admission
    (GenerationServer.submit/adopt know the budget before dispatch); the
    per-dispatch ``int(jnp.max(pos))`` here is a device→host sync the hot
    decode loop must not pay, so it is opt-in only."""
    if os.environ.get("DL4J_TPU_PAGED_DEBUG") != "1":
        return
    if isinstance(pos, jax.core.Tracer):
        return
    hi = int(jnp.max(pos))
    if hi + T > NP * ps:
        raise ValueError(
            f"paged KV overflow: position {hi} + {T} new tokens > "
            f"block table capacity {NP} pages x {ps} = {NP * ps}")


def scaled_dot_attention(q, k, v, *, causal: bool = False, mask=None):
    """softmax(q k^T / sqrt(d)) v over [..., T, d] arrays.

    mask: [B, T] validity of the KEY positions (broadcast over heads).
    """
    d = q.shape[-1]
    logits = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    T_q, T_k = logits.shape[-2], logits.shape[-1]
    if causal:
        causal_mask = jnp.tril(jnp.ones((T_q, T_k), bool))
        logits = jnp.where(causal_mask, logits, NEG_INF)
    if mask is not None:
        key_mask = mask.astype(bool)[:, None, None, :]  # [B,1,1,Tk]
        logits = jnp.where(key_mask, logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", w, v)


@register_serializable
@dataclass
class SelfAttentionLayer(BaseLayer):
    """Multi-head self-attention over [B, T, F] (post-reference-vintage DL4J
    SelfAttentionLayer; here with projection output Wo and optional causal
    masking for autoregressive stacks)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    causal: bool = False
    project_input: bool = True
    # KV-cache capacity for streaming decode (rnn_time_step); caches are
    # allocated lazily per stream, so this costs nothing until streaming
    max_cache: int = 512
    # Accelerated-kernel switch (the AlgoMode / cuDNN-helper analog,
    # reference: ConvolutionLayer.java:68-79 reflective helper load):
    # "auto" uses the Pallas flash kernel whenever it supports the case
    # (incl. [B,T] key masks since round 5; T divisible by its block),
    # "pallas" forces it, "stock" forces the XLA softmax(QK^T)V path.
    helper: str = "auto"
    # Paged-decode read backend (the PagedAttentionHelper seam,
    # nn/conf/layers/paged_attention.py): "auto" walks the block table
    # with the Pallas kernel on TPU and falls back to the gather-then-
    # attend XLA path elsewhere; "pallas"/"xla" force a backend (forced
    # pallas off-TPU runs in interpret mode — the CI parity config).
    # Resolution is trace-time static; serving program caches key on it.
    paged_attention: str = "auto"

    #: Tensor-parallel mesh for the paged decode path. Deliberately a
    #: plain CLASS attribute (no dataclass annotation): a live
    #: ``jax.sharding.Mesh`` is host runtime state, not layer config, so
    #: it must never serialize with the net. ``GenerationServer(mesh=)``
    #: pushes it per-instance and restores the prior value on close()
    #: (the same restore-on-close discipline as ``paged_attention``).
    #: When set, ``_paged_forward`` splits the write-scatter + attend
    #: head-parallel over the mesh's ``model`` axis; projections and
    #: page routing stay replicated, so outputs are bit-identical to the
    #: single-chip path at every tp (the only collective is an exact
    #: all-gather of disjoint per-head contexts before Wo).
    paged_mesh = None

    INPUT_KIND = "rnn"
    DEFAULT_ACTIVATION = "identity"
    #: projection weights eligible for int8 per-output-channel
    #: quantization (optimize/quantize.py); dequant is fused into the
    #: einsum epilogue by _proj
    QUANT_PARAMS = ("Wq", "Wk", "Wv", "Wo")

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = input_type.size
        if self.n_out == 0:
            self.n_out = self.n_in

    def validate(self) -> None:
        super().validate()
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out={self.n_out} not divisible by "
                             f"n_heads={self.n_heads}")

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def param_order(self):
        return ["Wq", "Wk", "Wv", "Wo", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        kq, kk, kv, ko = jax.random.split(rng, 4)
        D, O = self.n_in, self.n_out
        return {
            "Wq": self._init_w(kq, (D, O), D, O, dtype),
            "Wk": self._init_w(kk, (D, O), D, O, dtype),
            "Wv": self._init_w(kv, (D, O), D, O, dtype),
            "Wo": self._init_w(ko, (O, O), O, O, dtype),
            "b": jnp.full((O,), self.bias_init, dtype),
        }

    def _split_heads(self, x):
        B, T, O = x.shape
        H = self.n_heads
        return x.reshape(B, T, H, O // H).transpose(0, 2, 1, 3)  # [B,H,T,d]

    def _proj(self, params, x, name, spec="btf,fo->bto"):
        """One projection matmul, serving int8-quantized weights when
        the params tree carries a ``<name>_scale`` sibling: the
        per-output-channel dequant is fused into the einsum epilogue
        (``(x @ W_q.astype(x)) * scale``), which XLA folds — weights
        stay int8 in memory. The scale's presence is pytree structure,
        so f32 and quantized trees each trace their own program and the
        f32 math is untouched."""
        w = params[name]
        scale = params.get(name + "_scale")
        if scale is None:
            return jnp.einsum(spec, x, w)
        return (jnp.einsum(spec, x, w.astype(x.dtype)) * scale).astype(
            x.dtype)

    def _attend(self, q, k, v, mask):
        from deeplearning4j_tpu.ops import pallas_attention as pa

        if self.helper not in ("auto", "pallas", "stock"):
            raise ValueError(f"Unknown helper '{self.helper}'")
        use_pallas = self.helper == "pallas" or (
            self.helper == "auto"
            and pa.supports(q.shape, mask=mask, dtype=q.dtype))
        if use_pallas:
            return pa.flash_attention(q, k, v, causal=self.causal,
                                      mask=mask)
        return scaled_dot_attention(q, k, v, causal=self.causal, mask=mask)

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        if "kpages" in state:
            return self._paged_forward(params, state, x, mask=mask)
        if "kcache" in state:
            return self._streaming_forward(params, state, x, mask=mask)
        x = self.apply_input_dropout(x, train=train, rng=rng)
        q = self._split_heads(self._proj(params, x, "Wq"))
        k = self._split_heads(self._proj(params, x, "Wk"))
        v = self._split_heads(self._proj(params, x, "Wv"))
        o = self._attend(q, k, v, mask)
        B, H, T, d = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(B, T, H * d)
        out = self._proj(params, o, "Wo", "bto,op->btp") + params["b"]
        if mask is not None:
            out = out * mask.astype(out.dtype)[:, :, None]
        return self.act()(out), state

    # ------------------------------------------------- streaming decode
    def init_paged_carry(self, pages: int, page_size: int,
                         dtype=jnp.float32, kv_dtype=None) -> dict:
        """KV cache as a POOL of fixed-size pages (vLLM-style) instead of
        one contiguous [B, max_cache] strip per stream. The pool is shared
        by every slot of a serving batch: a ``[B, n_pages]`` block table
        (passed per call in ``state``) maps each row to its page list, so
        HBM cost is proportional to tokens actually resident — and two
        rows whose block tables name the same page share it (copy-on-write
        is the CALLER's job: this layer never checks refcounts, it just
        reads/writes where the table points). Only causal layers stream;
        non-causal layers return no carry (same rule as
        ``init_streaming_carry``).

        ``kv_dtype="int8"`` stores pages int8 with per-page-row f32
        scales (``kscales``/``vscales``, one scale per token per head):
        writes quantize, gathers dequantize — ~4x less HBM per resident
        token at a bounded accuracy delta."""
        if not self.causal:
            return {}
        H = self.n_heads
        d = self.n_out // H
        if kv_dtype == "int8":
            return {
                "kpages": jnp.zeros((pages, H, page_size, d), jnp.int8),
                "vpages": jnp.zeros((pages, H, page_size, d), jnp.int8),
                "kscales": jnp.zeros((pages, H, page_size), jnp.float32),
                "vscales": jnp.zeros((pages, H, page_size), jnp.float32),
            }
        if kv_dtype is not None:
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                             "(None or 'int8')")
        return {
            "kpages": jnp.zeros((pages, H, page_size, d), dtype),
            "vpages": jnp.zeros((pages, H, page_size, d), dtype),
        }

    def init_streaming_carry(self, batch: int, dtype=jnp.float32,
                             kv_dtype=None) -> dict:
        """KV cache for incremental decode (the transformer analog of the
        LSTM's h/c streaming state behind rnnTimeStep): keys/values of
        already-consumed positions stay cached, so each new token costs
        one attention row instead of a full O(T^2) re-forward. Only
        causal layers can stream — a non-causal layer would need future
        tokens — so they return no carry (per-chunk attention then
        applies, matching the pre-cache behavior).

        ``kv_dtype="int8"`` is the dense-strip analog of the int8 paged
        pool: int8 caches plus per-token-per-head f32 ``kscale``/
        ``vscale`` strips."""
        if not self.causal:
            return {}
        H = self.n_heads
        d = self.n_out // H
        if kv_dtype == "int8":
            return {
                "kcache": jnp.zeros((batch, H, self.max_cache, d), jnp.int8),
                "vcache": jnp.zeros((batch, H, self.max_cache, d), jnp.int8),
                "kscale": jnp.zeros((batch, H, self.max_cache), jnp.float32),
                "vscale": jnp.zeros((batch, H, self.max_cache), jnp.float32),
                "cache_pos": jnp.zeros((), jnp.int32),
            }
        if kv_dtype is not None:
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                             "(None or 'int8')")
        return {
            "kcache": jnp.zeros((batch, H, self.max_cache, d), dtype),
            "vcache": jnp.zeros((batch, H, self.max_cache, d), dtype),
            "cache_pos": jnp.zeros((), jnp.int32),
        }

    @staticmethod
    def _quantize_kv(t):
        """Absmax per-(row, head, token) int8 of a fresh KV chunk
        ``[B, H, T, d]`` -> (int8 values, f32 scales ``[B, H, T]``).
        All-zero rows get scale 0 and reconstruct as exact zeros."""
        m = jnp.max(jnp.abs(t), axis=-1)
        scale = (m / 127.0).astype(jnp.float32)
        safe = jnp.where(scale > 0, scale, 1.0).astype(t.dtype)
        q = jnp.clip(jnp.round(t / safe[..., None]), -127, 127).astype(
            jnp.int8)
        return q, scale

    def _streaming_forward(self, params, state, x, mask=None):
        """Incremental decode over the KV cache.

        ``cache_pos`` may be a scalar (one shared stream position — the
        classic rnn_time_step path) or a ``[B]`` vector of PER-ROW
        positions (slot-pooled serving: each batch row is an independent
        sequence at its own depth, so attention is masked per-row by that
        row's true length and the new chunk is scattered at per-row
        offsets).

        ``mask``: optional ``[B, T]`` validity of the NEW chunk's
        positions. Masked positions contribute no attention keys and
        their outputs are zeroed (matching the non-streaming path), but
        they still occupy cache columns — ``cache_pos`` advances by the
        full chunk length; callers that right-pad (bucketed prefill) must
        set their own true-length watermark afterwards. Any other mask
        shape is an error: silently dropping it would let padded garbage
        attend as real keys.
        """
        B, T, _ = x.shape
        kc, vc, pos = state["kcache"], state["vcache"], state["cache_pos"]
        Tmax = kc.shape[2]
        per_row = getattr(pos, "ndim", 0) == 1
        if not isinstance(pos, jax.core.Tracer):
            hi = int(jnp.max(pos)) if per_row else int(pos)
            if hi + T > Tmax:
                raise ValueError(
                    f"KV cache overflow: position {hi} + {T} new tokens "
                    f"> max_cache {Tmax}; raise SelfAttentionLayer.max_cache "
                    "or rnn_clear_previous_state() to start a new stream")
        if mask is not None:
            mask = jnp.asarray(mask)
            if mask.shape != (B, T):
                raise ValueError(
                    f"streaming attention mask must be [batch, chunk] = "
                    f"({B}, {T}), got {mask.shape}; per-feature or "
                    "flattened masks cannot be applied to the KV cache")
        q = self._split_heads(self._proj(params, x, "Wq"))
        k = self._split_heads(self._proj(params, x, "Wk"))
        v = self._split_heads(self._proj(params, x, "Wv"))
        # int8 KV mode is keyed by the carry STRUCTURE (scale strips
        # present), so it is part of the jit cache key — never a retrace
        # hazard. Fresh chunks quantize on write; attention reads the
        # dequantized view (XLA fuses the widen into the QK^T matmul).
        quant = "kscale" in state
        ks = vs = ksc = vsc = None
        if quant:
            ks, vs = state["kscale"], state["vscale"]
            k, ksc = self._quantize_kv(k)
            v, vsc = self._quantize_kv(v)
        if per_row:
            # write each row's chunk at its own offset as a vmapped
            # dynamic-update-slice: unlike an advanced-index scatter
            # (which XLA CPU lowers to an element loop) this aliases
            # in-place inside donated decode scans — the slot-pooled
            # decode step pays this write 2x per layer per token
            z = jnp.zeros((), pos.dtype)
            kc = jax.vmap(
                lambda c, u, p: jax.lax.dynamic_update_slice(
                    c, u, (z, p, z)))(kc, k.astype(kc.dtype), pos)
            vc = jax.vmap(
                lambda c, u, p: jax.lax.dynamic_update_slice(
                    c, u, (z, p, z)))(vc, v.astype(vc.dtype), pos)
            if quant:
                ks = jax.vmap(
                    lambda c, u, p: jax.lax.dynamic_update_slice(
                        c, u, (z, p)))(ks, ksc, pos)
                vs = jax.vmap(
                    lambda c, u, p: jax.lax.dynamic_update_slice(
                        c, u, (z, p)))(vs, vsc, pos)
        else:
            z = jnp.zeros((), jnp.int32)  # index dtypes must all match pos's
            kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                              (z, z, pos, z))
            vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                              (z, z, pos, z))
            if quant:
                ks = jax.lax.dynamic_update_slice(ks, ksc, (z, z, pos))
                vs = jax.lax.dynamic_update_slice(vs, vsc, (z, z, pos))
        if quant:
            kd = kc.astype(q.dtype) * ks[..., None].astype(q.dtype)
            vd = vc.astype(q.dtype) * vs[..., None].astype(q.dtype)
        else:
            kd, vd = kc, vc
        d = q.shape[-1]
        logits = jnp.einsum("bhtd,bhkd->bhtk", q, kd) / jnp.sqrt(
            jnp.asarray(d, q.dtype))
        col = jnp.arange(Tmax)[None, None, None, :]
        row = jnp.arange(T)[None, None, :, None]
        p4 = pos.reshape(-1, 1, 1, 1) if per_row else pos
        logits = jnp.where(col <= p4 + row, logits, NEG_INF)
        if mask is not None:
            # key validity over the cache axis: columns belonging to this
            # chunk take the chunk mask; everything older stays valid
            colv = jnp.arange(Tmax)[None, :]
            rel = colv - (pos[:, None] if per_row else pos)     # [B?,Tmax]
            rel = jnp.broadcast_to(rel, (B, Tmax))
            chunk_valid = jnp.take_along_axis(
                mask.astype(bool), jnp.clip(rel, 0, T - 1), axis=1)
            key_valid = jnp.where((rel >= 0) & (rel < T), chunk_valid, True)
            logits = jnp.where(key_valid[:, None, None, :], logits, NEG_INF)
        o = jnp.einsum("bhtk,bhkd->bhtd",
                       jax.nn.softmax(logits, axis=-1), vd)
        if self.paged_mesh is not None:
            # tensor-parallel decode gathers the paged pool into dense
            # views sharded on the head axis; GSPMD keeps every op so
            # far per-head (no cross-shard reduction). Pin the contexts
            # replicated HERE — an exact all-gather of disjoint head
            # slices — so the head-merging reshape below can never turn
            # the Wo contraction into a partial-sum all-reduce (float
            # reordering would break tp-vs-single-chip bit-exactness).
            from jax.sharding import NamedSharding, PartitionSpec

            o = jax.lax.with_sharding_constraint(
                o, NamedSharding(self.paged_mesh, PartitionSpec()))
        o = o.transpose(0, 2, 1, 3).reshape(B, T, self.n_out)
        out = self._proj(params, o, "Wo", "bto,op->btp") + params["b"]
        if mask is not None:
            out = out * mask.astype(out.dtype)[:, :, None]
        new_state = dict(state)
        new_state["kcache"] = kc
        new_state["vcache"] = vc
        if quant:
            new_state["kscale"] = ks
            new_state["vscale"] = vs
        new_state["cache_pos"] = pos + T
        return self.act()(out), new_state

    def _paged_forward(self, params, state, x, mask=None):
        """Incremental decode over a paged KV pool (see init_paged_carry).

        ``state`` carries, besides the pool itself:
          - ``block_table``: ``[B, n_pages]`` int32, row b's i-th logical
            page lives in pool page ``block_table[b, i]``. Rows may share
            pages (prefix sharing); the caller guarantees copy-on-write,
            i.e. a page a row WRITES into this call is owned by that row
            alone (or is a designated garbage page).
          - ``cache_pos``: ``[B]`` per-row stream positions, exactly as in
            the per-row ``_streaming_forward`` path.

        The attend over the resident pages routes through the
        PagedAttentionHelper seam (nn/conf/layers/paged_attention.py):
        the XLA backend attends over the gathered
        ``[B, H, n_pages*page_size, d]`` view — the dense per-row path
        verbatim, so outputs are bit-identical to a contiguous cache of
        capacity ``n_pages * page_size`` holding the same tokens — and
        the Pallas backend reads pages in place via the block table,
        parity-pinned bitwise against the XLA path. The chunk WRITE
        below never enters the seam: every backend sees the same
        scatter, garbage-page routing and COW contract.

        Capacity is the caller's page-accounting admission to enforce
        (GenerationServer budgets pages before dispatch); set
        ``DL4J_TPU_PAGED_DEBUG=1`` to re-enable the per-dispatch
        host-sync overflow assert when debugging a new caller.
        """
        B, T, _ = x.shape
        kp, vp = state["kpages"], state["vpages"]
        bt = state["block_table"]
        pos = state["cache_pos"]
        if getattr(pos, "ndim", 0) != 1:
            raise ValueError("paged attention requires per-row [B] "
                             f"cache_pos, got shape {getattr(pos, 'shape', ())}")
        ps = kp.shape[2]
        NP = bt.shape[1]
        _debug_paged_overflow(pos, T, NP, ps)
        if mask is not None:
            mask = jnp.asarray(mask)
            if mask.shape != (B, T):
                raise ValueError(
                    f"streaming attention mask must be [batch, chunk] = "
                    f"({B}, {T}), got {mask.shape}; per-feature or "
                    "flattened masks cannot be applied to the KV cache")
        q = self._split_heads(self._proj(params, x, "Wq"))
        k = self._split_heads(self._proj(params, x, "Wk"))
        v = self._split_heads(self._proj(params, x, "Wv"))
        # int8 pool (scale planes present — a structure check, so part
        # of the jit key): quantize the fresh chunk on write, with its
        # per-token-per-head scales scattered through the SAME page
        # routing (masked columns land on garbage page 0 for values and
        # scales alike)
        quant = "kscales" in state
        ksp = vsp = ksc = vsc = None
        if quant:
            ksp, vsp = state["kscales"], state["vscales"]
            k, ksc = self._quantize_kv(k)
            v, vsc = self._quantize_kv(v)
        # scatter the chunk at per-row offsets, routed through the block
        # table: logical position p of row b lands in pool page
        # bt[b, p // ps] at offset p % ps. Advanced indices [B,T] straddle
        # the head slice, so the updated value carries [B,T,H,d] layout.
        t_abs = pos[:, None] + jnp.arange(T)[None, :]            # [B,T]
        pg = jnp.take_along_axis(bt, jnp.minimum(t_abs // ps, NP - 1),
                                 axis=1)                         # [B,T]
        off = t_abs % ps
        if mask is not None:
            # masked (right-padding) columns write pool page 0 — the
            # caller-reserved garbage sink — so padded prefill chunks
            # never dirty real pages and a row needs page backing for
            # its true tokens only
            pg = jnp.where(mask.astype(bool), pg, 0)
        if self.paged_mesh is not None:
            kp, vp, ksp, vsp, o = self._sharded_write_attend(
                q, k, v, ksc, vsc, kp, vp, ksp, vsp, bt, pos, pg, off,
                mask, quant, ps, NP)
        else:
            kp = kp.at[pg, :, off, :].set(
                k.astype(kp.dtype).transpose(0, 2, 1, 3))
            vp = vp.at[pg, :, off, :].set(
                v.astype(vp.dtype).transpose(0, 2, 1, 3))
            if quant:
                ksp = ksp.at[pg, :, off].set(ksc.transpose(0, 2, 1))
                vsp = vsp.at[pg, :, off].set(vsc.transpose(0, 2, 1))
            # read side: attend over the resident pages through the
            # selected helper backend. Resolution is trace-time static
            # (the knob is host config, the geometry is shapes), so each
            # backend family traces its own program — never a retrace
            # hazard.
            from deeplearning4j_tpu.nn.conf.layers import paged_attention as ppa

            backend = ppa.resolve_paged_backend(
                self.paged_attention, page_size=ps,
                head_dim=self.n_out // self.n_heads, n_pages=NP, quant=quant)
            o = ppa.paged_attend(backend, q, kp, vp, bt, pos, mask=mask,
                                 kscales=ksp, vscales=vsp)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, self.n_out)
        out = self._proj(params, o, "Wo", "bto,op->btp") + params["b"]
        if mask is not None:
            out = out * mask.astype(out.dtype)[:, :, None]
        new_state = dict(state)
        new_state["kpages"] = kp
        new_state["vpages"] = vp
        if quant:
            new_state["kscales"] = ksp
            new_state["vscales"] = vsp
        new_state["cache_pos"] = pos + T
        return self.act()(out), new_state

    def _sharded_write_attend(self, q, k, v, ksc, vsc, kp, vp, ksp, vsp,
                              bt, pos, pg, off, mask, quant, ps, NP):
        """Head-parallel write + attend over ``self.paged_mesh``.

        The math is the single-chip ``_paged_forward`` body verbatim,
        run per-shard on the ``H/tp`` local head slice: q/k/v chunks and
        the pool leaves split on their head axis, the block table / page
        routing replicated (every shard scatters into the SAME pages of
        its own head slice). Attention contexts are independent per
        head, so the shard outputs are disjoint and the head-axis
        all-gather of ``o`` (forced by the caller's replication
        constraint before Wo) is exact concatenation — no reduction, no
        float reordering — which is what makes tp>1 outputs bit-exact
        against tp=1. Both helper backends serve the local view
        unchanged: the XLA gather sees an ``[P, H/tp, ps, d]`` pool, the
        Pallas kernel a ``(B, H/tp, NP)`` grid.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.nn.conf.layers import paged_attention as ppa
        from deeplearning4j_tpu.parallel.mesh import (MODEL_AXIS,
                                                      shard_map_compat)

        mesh = self.paged_mesh
        head4 = P(None, MODEL_AXIS, None, None)  # [B,H,T,d] / [P,H,ps,d]
        head3 = P(None, MODEL_AXIS, None)        # [B,H,T]   / [P,H,ps]
        head_dim = self.n_out // self.n_heads
        has_mask = mask is not None

        def local(q, k, v, kp, vp, bt, pos, pg, off, ksc, vsc, ksp, vsp,
                  mask):
            kp = kp.at[pg, :, off, :].set(
                k.astype(kp.dtype).transpose(0, 2, 1, 3))
            vp = vp.at[pg, :, off, :].set(
                v.astype(vp.dtype).transpose(0, 2, 1, 3))
            if quant:
                ksp = ksp.at[pg, :, off].set(ksc.transpose(0, 2, 1))
                vsp = vsp.at[pg, :, off].set(vsc.transpose(0, 2, 1))
            backend = ppa.resolve_paged_backend(
                self.paged_attention, page_size=ps, head_dim=head_dim,
                n_pages=NP, quant=quant)
            o = ppa.paged_attend(backend, q, kp, vp, bt, pos,
                                 mask=mask if has_mask else None,
                                 kscales=ksp, vscales=vsp)
            out = [kp, vp, o]
            if quant:
                out += [ksp, vsp]
            return tuple(out)

        # None operands have no leaves, so any placeholder spec works;
        # the quant/mask STRUCTURE is already part of the jit cache key
        in_specs = (head4, head4, head4, head4, head4, P(), P(), P(), P(),
                    head3 if quant else P(), head3 if quant else P(),
                    head3 if quant else P(), head3 if quant else P(),
                    P())
        out_specs = (head4, head4, head4) + ((head3, head3) if quant
                                             else ())
        fn = shard_map_compat(local, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check=False)
        res = fn(q, k, v, kp, vp, bt, pos, pg, off, ksc, vsc, ksp, vsp,
                 mask if has_mask else None)
        kp, vp, o = res[0], res[1], res[2]
        if quant:
            ksp, vsp = res[3], res[4]
        # replicate the per-head contexts before the (replicated) Wo
        # projection: an exact all-gather — each shard contributed a
        # disjoint head slice, so no arithmetic happens in the collective
        o = jax.lax.with_sharding_constraint(
            o, NamedSharding(mesh, P()))
        return kp, vp, ksp, vsp, o


@register_serializable
@dataclass
class PositionalEncodingLayer(Layer):
    """Add the fixed sinusoidal position table to a [B, T, F] sequence
    (Vaswani et al. encoding; parameterless, so serde is trivial and the
    table is a compile-time constant folded into the XLA program).

    Beyond reference parity: exists (with LayerNormalization) so
    transformer stacks are buildable first-class — the 2017-era reference
    predates them.
    """

    max_wavelength: float = 10000.0

    INPUT_KIND = "rnn"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init_streaming_carry(self, batch: int, dtype=jnp.float32) -> dict:
        # streaming decode: chunk t must receive the encoding of its
        # ABSOLUTE position, so the consumed-token count is carried
        return {"cache_pos": jnp.zeros((), jnp.int32)}

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        T, F = x.shape[-2], x.shape[-1]
        start = state.get("cache_pos")
        if start is not None and getattr(start, "ndim", 0) == 1:
            # per-row stream positions (slot-pooled decode): [B, T, 1]
            pos = start.astype(jnp.float32)[:, None, None] \
                + jnp.arange(T, dtype=jnp.float32)[None, :, None]
        else:
            pos = jnp.arange(T, dtype=jnp.float32)[:, None] \
                + (0.0 if start is None else start.astype(jnp.float32))
        half = (F + 1) // 2
        freq = jnp.exp(-jnp.log(self.max_wavelength)
                       * jnp.arange(half, dtype=jnp.float32) / max(half, 1))
        ang = pos * freq                          # [..., T, half]
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)[..., :F]
        out = x + pe.astype(x.dtype)
        if start is None:
            return out, state
        new_state = dict(state)
        new_state["cache_pos"] = start + T
        return out, new_state
