"""Global pooling (reference: nn/conf/layers/GlobalPoolingLayer +
nn/layers/pooling/GlobalPoolingLayer.java). Mask-aware over time for RNN data."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.utils.serde import register_serializable


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@register_serializable
@dataclass
class GlobalPoolingLayer(Layer):
    """Pool over time ([B,T,F] -> [B,F]) or space ([B,H,W,C] -> [B,C]).

    For masked time series, masked steps are excluded (MAX uses -inf fill, AVG/SUM
    exclude masked elements from numerator/denominator) — matching the reference's
    masked pooling semantics.
    """

    pooling_type: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "convolutional":
            return InputType.feed_forward(input_type.channels)
        return input_type

    def feed_forward_mask(self, mask, current_mask_state: str = "active"):
        return None  # pooling collapses the time dimension; mask is consumed

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        if x.ndim == 3:
            axes = (1,)
        elif x.ndim == 4:
            axes = (1, 2)
        else:
            raise ValueError(f"GlobalPooling expects 3-D or 4-D input, got {x.shape}")
        pt = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = mask.astype(x.dtype)[:, :, None]
            if pt == "max":
                out = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif pt == "sum":
                out = jnp.sum(x * m, axis=1)
            elif pt == "avg":
                out = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            elif pt == "pnorm":
                p = float(self.pnorm)
                out = jnp.sum((jnp.abs(x) * m) ** p, axis=1) ** (1.0 / p)
            else:
                raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
            return out, state
        if pt == "max":
            out = jnp.max(x, axis=axes)
        elif pt == "sum":
            out = jnp.sum(x, axis=axes)
        elif pt == "avg":
            out = jnp.mean(x, axis=axes)
        elif pt == "pnorm":
            p = float(self.pnorm)
            out = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return out, state
