"""Layer base classes.

Reference: nn/conf/layers/Layer.java + BaseLayer hyperparameter fields, and the
runtime contract of nn/api/Layer.java:37 (activate/backprop/masking). Here the
contract is functional:

- ``init_params(rng, dtype) -> dict[str, Array]``  (param shapes; flat-buffer order
  given by ``param_order``)
- ``init_state() -> dict``                          (e.g. BN running stats)
- ``forward(params, state, x, *, mask, train, rng) -> (out, new_state)``

``forward`` must be jax-traceable: no data-dependent Python control flow, static
shapes only, so whole networks compile to one XLA program.

Hyperparameter inheritance matches the reference's builder: fields left as ``None``
on a layer are filled from the global ``NeuralNetConfiguration`` at build time
(``finalize``), falling back to per-class defaults (``DEFAULT_ACTIVATION`` etc.).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.weights import Distribution, init_weight
from deeplearning4j_tpu.ops.activations import Activation, get_activation


@dataclass
class Layer:
    """Base for all layer configs. ``dropout`` is the probability of dropping each
    input activation (inverted dropout on the layer *input*, matching the placement
    in the reference's BaseLayer.activate -> Dropout.applyDropout,
    nn/layers/BaseLayer.java:540-551)."""

    name: Optional[str] = None
    dropout: Optional[float] = None
    # Gradient normalization/clipping applied between backprop and the
    # updater (reference: nn/conf/GradientNormalization.java, applied in
    # BaseMultiLayerUpdater.preApply :310-352). Modes: none |
    # renormalize_l2_per_layer | renormalize_l2_per_param_type |
    # clip_element_wise_absolute_value | clip_l2_per_layer |
    # clip_l2_per_param_type
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    # what array kind this layer consumes: ff | cnn | rnn | any
    INPUT_KIND = "any"

    # ---- config plumbing -------------------------------------------------------
    def finalize(self, g=None) -> None:
        """Fill None fields from the global conf ``g`` (NeuralNetConfiguration)."""
        if self.dropout is None:
            self.dropout = (g.dropout if g is not None and g.dropout is not None
                            else 0.0)
        if self.gradient_normalization is None:
            self.gradient_normalization = (
                g.gradient_normalization
                if g is not None and g.gradient_normalization is not None
                else "none")
        if self.gradient_normalization_threshold is None:
            self.gradient_normalization_threshold = (
                g.gradient_normalization_threshold
                if g is not None
                and g.gradient_normalization_threshold is not None else 1.0)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type: InputType) -> None:
        """Infer nIn-like fields from the previous layer's output type (parity with
        FeedForwardLayer.setNIn auto-config)."""

    def validate(self) -> None:
        """Config sanity, run at build() time so bad configs fail with a
        named-layer message instead of a raw XLA shape error at fit time
        (reference: the checks behind exceptions/TestInvalidConfigurations
        — nIn/nOut == 0 raise at init, DL4JInvalidConfigException).

        Only WEIGHTED layers need n_in/n_out: paramless passthroughs
        (LastTimeStep, ActivationLayer, ...) inherit the fields without
        consuming them."""
        if not self.param_order():
            return
        for attr in ("n_in", "n_out"):
            v = getattr(self, attr, None)
            if isinstance(v, int) and v <= 0:
                label = self.name or type(self).__name__
                hint = (" (set an InputType on the builder, or pass "
                        f"{attr} explicitly)" if attr == "n_in" else "")
                raise ValueError(
                    f"Invalid configuration for layer '{label}': "
                    f"{attr} must be > 0, got {v}{hint}")

    # ---- params ----------------------------------------------------------------
    def param_order(self) -> list[str]:
        return []

    def init_params(self, rng, dtype=jnp.float32) -> dict:
        return {}

    def init_state(self, dtype=jnp.float32) -> dict:
        return {}

    def init_streaming_carry(self, batch: int, dtype=jnp.float32) -> dict:
        """Initial carry for streaming inference (rnn_time_step). LSTMs
        need none (their h/c default lazily to zeros); attention layers
        return a KV cache here so incremental decode is O(T) per token
        instead of re-running the full O(T^2) forward."""
        return {}

    def has_params(self) -> bool:
        return bool(self.param_order())

    def regularization(self, params: dict):
        """L1/L2 penalty contribution (reference: BaseLayer.calcL1/calcL2)."""
        return 0.0

    def regularization_grad(self, params: dict) -> dict:
        """Analytic penalty gradient per leaf (see BaseLayer override)."""
        return {}

    # ---- compute ---------------------------------------------------------------
    def forward(self, params: dict, state: dict, x, *, mask=None, train: bool = False,
                rng=None):
        raise NotImplementedError

    def apply_input_dropout(self, x, *, train: bool, rng):
        p = self.dropout or 0.0
        if train and p > 0.0 and rng is not None:
            keep = 1.0 - p
            m = jax.random.bernoulli(rng, keep, x.shape)
            return jnp.where(m, x / keep, 0.0)
        return x

    def feed_forward_mask(self, mask, current_mask_state: str = "active"):
        """How this layer transforms a time-mask (reference: Layer.feedForwardMaskArray)."""
        return mask


@dataclass
class BaseLayer(Layer):
    """Layers with weights: activation + init + regularisation hyperparams."""

    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[Distribution] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    # Per-layer learning-rate override (reference: BaseLayer.learningRate /
    # biasLearningRate). None -> use the global updater learning rate.
    learning_rate: Optional[float] = None
    bias_learning_rate: Optional[float] = None

    DEFAULT_ACTIVATION = "sigmoid"

    def finalize(self, g=None) -> None:
        super().finalize(g)
        if self.activation is None:
            self.activation = ((g.activation if g is not None else None)
                               or self.DEFAULT_ACTIVATION)
        if self.weight_init is None:
            self.weight_init = ((g.weight_init if g is not None else None) or "xavier")
        if self.dist is None and g is not None:
            self.dist = g.dist
        if self.bias_init is None:
            self.bias_init = (g.bias_init if g is not None and g.bias_init is not None
                              else 0.0)
        for f, gf in (("l1", "l1"), ("l2", "l2"), ("l1_bias", "l1_bias"),
                      ("l2_bias", "l2_bias")):
            if getattr(self, f) is None:
                gv = getattr(g, gf, None) if g is not None else None
                setattr(self, f, gv if gv is not None else 0.0)

    def act(self) -> Activation:
        return get_activation(self.activation or self.DEFAULT_ACTIVATION)

    def _init_w(self, rng, shape, fan_in, fan_out, dtype):
        return init_weight(rng, shape, fan_in, fan_out,
                           self.weight_init or "xavier", self.dist, dtype)

    def bias_param_names(self) -> frozenset:
        """Params that take l1_bias/l2_bias instead of l1/l2 (reference: the
        ParamInitializer weight/bias split used by conf.getL2ByParam). Layers with
        non-'b' bias names override this explicitly."""
        return frozenset({"b"})

    def regularization(self, params: dict):
        reg = 0.0
        l1 = self.l1 or 0.0
        l2 = self.l2 or 0.0
        l1b = self.l1_bias or 0.0
        l2b = self.l2_bias or 0.0
        biases = self.bias_param_names()
        for k, v in params.items():
            if k in biases:
                if l2b > 0:
                    reg = reg + 0.5 * l2b * jnp.sum(v * v)
                if l1b > 0:
                    reg = reg + l1b * jnp.sum(jnp.abs(v))
            else:
                if l2 > 0:
                    reg = reg + 0.5 * l2 * jnp.sum(v * v)
                if l1 > 0:
                    reg = reg + l1 * jnp.sum(jnp.abs(v))
        return reg

    def regularization_grad(self, params: dict) -> dict:
        """Analytic d(regularization)/d(param) per leaf: l2*W + l1*sign(W).

        The train step adds these to the data-loss gradients instead of
        differentiating ``regularization()`` — same math (the penalty is a
        closed form), but the elementwise terms fuse into the updater while
        autodiff-through-reductions materialised a separate backward pass
        (measured 30% of the ResNet50 step, profiles/README.md). This is
        also the reference's own architecture: DL4J applies l1/l2 inside
        the updater (BaseUpdater.postApply), not through backprop."""
        l1 = self.l1 or 0.0
        l2 = self.l2 or 0.0
        l1b = self.l1_bias or 0.0
        l2b = self.l2_bias or 0.0
        biases = self.bias_param_names()
        out = {}
        for k, v in params.items():
            c2, c1 = (l2b, l1b) if k in biases else (l2, l1)
            g = None
            if c2 > 0:
                g = c2 * v
            if c1 > 0:
                g = (0 if g is None else g) + c1 * jnp.sign(v)
            if g is not None:
                out[k] = g
        return out


@dataclass
class FeedForwardLayer(BaseLayer):
    """Dense-style layers with explicit nIn/nOut (reference: FeedForwardLayer.java)."""

    n_in: int = 0
    n_out: int = 0

    INPUT_KIND = "ff"

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)
