"""Variational autoencoder layer + reconstruction distributions.

Reference: nn/layers/variational/VariationalAutoencoder.java (1141 LoC of
hand-written fwd/bwd) and nn/conf/layers/variational/* reconstruction distributions.
Here the whole -ELBO (reparameterised sample + reconstruction log-prob + analytic
KL(q||N(0,I))) is one differentiable jax expression; pretraining just runs jax.grad
over it.

Supervised forward (when the VAE sits mid-network) outputs the latent mean, matching
the reference's activate().
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.losses import get_loss
from deeplearning4j_tpu.utils.serde import register_serializable

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


@register_serializable
@dataclass
class GaussianReconstructionDistribution:
    """p(x|z) = N(mean(z), exp(logvar(z))). Head width = 2 * n_visible."""

    activation: str = "identity"

    def head_size(self, n_visible: int) -> int:
        return 2 * n_visible

    def neg_log_prob(self, x, head_pre):
        n = x.shape[-1]
        mean = get_activation(self.activation)(head_pre[..., :n])
        logvar = head_pre[..., n:]
        var = jnp.exp(logvar)
        return jnp.sum(_HALF_LOG_2PI + 0.5 * logvar + 0.5 * (x - mean) ** 2 / var,
                       axis=-1)

    def sample_mean(self, head_pre, n_visible):
        return get_activation(self.activation)(head_pre[..., :n_visible])


@register_serializable
@dataclass
class BernoulliReconstructionDistribution:
    """p(x|z) = Bernoulli(sigmoid(head)). Head width = n_visible."""

    activation: str = "sigmoid"

    def head_size(self, n_visible: int) -> int:
        return n_visible

    def neg_log_prob(self, x, head_pre):
        act = get_activation(self.activation)
        if self.activation == "sigmoid":
            z = head_pre
            per = jnp.maximum(z, 0.0) - z * x + jnp.log1p(jnp.exp(-jnp.abs(z)))
        else:
            p = jnp.clip(act(head_pre), 1e-7, 1.0 - 1e-7)
            per = -(x * jnp.log(p) + (1 - x) * jnp.log(1 - p))
        return jnp.sum(per, axis=-1)

    def sample_mean(self, head_pre, n_visible):
        return get_activation(self.activation)(head_pre)


@register_serializable
@dataclass
class ExponentialReconstructionDistribution:
    """p(x|z) = Exp(lambda = exp(head)). Head width = n_visible."""

    activation: str = "identity"

    def head_size(self, n_visible: int) -> int:
        return n_visible

    def neg_log_prob(self, x, head_pre):
        log_lambda = get_activation(self.activation)(head_pre)
        lam = jnp.exp(log_lambda)
        return jnp.sum(lam * x - log_lambda, axis=-1)

    def sample_mean(self, head_pre, n_visible):
        return 1.0 / jnp.exp(get_activation(self.activation)(head_pre))


@register_serializable
@dataclass
class LossFunctionWrapper:
    """Use a standard loss as reconstruction 'distribution' (reference:
    nn/conf/layers/variational/LossFunctionWrapper.java)."""

    loss: str = "mse"
    activation: str = "identity"

    def head_size(self, n_visible: int) -> int:
        return n_visible

    def neg_log_prob(self, x, head_pre):
        return get_loss(self.loss).per_example(x, head_pre,
                                               get_activation(self.activation))

    def sample_mean(self, head_pre, n_visible):
        return get_activation(self.activation)(head_pre)


@register_serializable
@dataclass
class CompositeReconstructionDistribution:
    """Different distributions over slices of the visible vector (reference:
    nn/conf/layers/variational/CompositeReconstructionDistribution.java)."""

    sizes: list = field(default_factory=list)          # visible units per component
    distributions: list = field(default_factory=list)  # one dist per component

    def head_size(self, n_visible: int) -> int:
        return sum(d.head_size(s) for d, s in zip(self.distributions, self.sizes))

    def neg_log_prob(self, x, head_pre):
        total = 0.0
        xi = 0
        hi = 0
        for d, s in zip(self.distributions, self.sizes):
            hs = d.head_size(s)
            total = total + d.neg_log_prob(x[..., xi:xi + s], head_pre[..., hi:hi + hs])
            xi += s
            hi += hs
        return total

    def sample_mean(self, head_pre, n_visible):
        outs = []
        hi = 0
        for d, s in zip(self.distributions, self.sizes):
            hs = d.head_size(s)
            outs.append(d.sample_mean(head_pre[..., hi:hi + hs], s))
            hi += hs
        return jnp.concatenate(outs, axis=-1)


@register_serializable
@dataclass
class VariationalAutoencoder(FeedForwardLayer):
    """VAE pretrain layer. n_in = visible size, n_out = latent size.

    ``encoder_layer_sizes``/``decoder_layer_sizes`` mirror the reference's
    encoderLayerSizes/decoderLayerSizes builder fields; ``pzx_activation`` is the
    activation for the q(z|x) mean head (reference: pzxActivationFunction).
    """

    encoder_layer_sizes: tuple = (100,)
    decoder_layer_sizes: tuple = (100,)
    reconstruction_distribution: object = None
    pzx_activation: str = "identity"
    n_samples: int = 1

    DEFAULT_ACTIVATION = "tanh"  # hidden-layer activation

    def __post_init__(self):
        if self.reconstruction_distribution is None:
            self.reconstruction_distribution = GaussianReconstructionDistribution()
        self.encoder_layer_sizes = tuple(self.encoder_layer_sizes)
        self.decoder_layer_sizes = tuple(self.decoder_layer_sizes)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def param_order(self):
        order = []
        for i in range(len(self.encoder_layer_sizes)):
            order += [f"eW{i}", f"eb{i}"]
        order += ["mW", "mb", "lW", "lb"]
        for i in range(len(self.decoder_layer_sizes)):
            order += [f"dW{i}", f"db{i}"]
        order += ["rW", "rb"]
        return order

    def bias_param_names(self):
        names = {f"eb{i}" for i in range(len(self.encoder_layer_sizes))}
        names |= {f"db{i}" for i in range(len(self.decoder_layer_sizes))}
        names |= {"mb", "lb", "rb"}
        return frozenset(names)

    def init_params(self, rng, dtype=jnp.float32):
        params = {}
        keys = jax.random.split(rng, 3 + len(self.encoder_layer_sizes)
                                + len(self.decoder_layer_sizes) + 1)
        ki = 0
        prev = self.n_in
        for i, size in enumerate(self.encoder_layer_sizes):
            params[f"eW{i}"] = self._init_w(keys[ki], (prev, size), prev, size, dtype)
            params[f"eb{i}"] = jnp.zeros((size,), dtype)
            prev = size
            ki += 1
        params["mW"] = self._init_w(keys[ki], (prev, self.n_out), prev, self.n_out, dtype)
        params["mb"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        params["lW"] = self._init_w(keys[ki], (prev, self.n_out), prev, self.n_out, dtype)
        params["lb"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        prev = self.n_out
        for i, size in enumerate(self.decoder_layer_sizes):
            params[f"dW{i}"] = self._init_w(keys[ki], (prev, size), prev, size, dtype)
            params[f"db{i}"] = jnp.zeros((size,), dtype)
            prev = size
            ki += 1
        head = self.reconstruction_distribution.head_size(self.n_in)
        params["rW"] = self._init_w(keys[ki], (prev, head), prev, head, dtype)
        params["rb"] = jnp.zeros((head,), dtype)
        return params

    def _encode(self, params, x):
        act = self.act()
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(jnp.dot(h, params[f"eW{i}"]) + params[f"eb{i}"])
        mean = get_activation(self.pzx_activation)(jnp.dot(h, params["mW"]) + params["mb"])
        logvar = jnp.dot(h, params["lW"]) + params["lb"]
        return mean, logvar

    def _decode(self, params, z):
        act = self.act()
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(jnp.dot(h, params[f"dW{i}"]) + params[f"db{i}"])
        return jnp.dot(h, params["rW"]) + params["rb"]

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        mean, _ = self._encode(params, x)
        return mean, state

    def pretrain_loss_per_example(self, params, x, rng):
        """-ELBO per example (reconstruction NLL + analytic KL to N(0, I))."""
        mean, logvar = self._encode(params, x)
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + mean ** 2 - 1.0 - logvar, axis=-1)
        total_recon = 0.0
        keys = jax.random.split(rng, self.n_samples)
        for i in range(self.n_samples):
            eps = jax.random.normal(keys[i], mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            head_pre = self._decode(params, z)
            total_recon = total_recon + self.reconstruction_distribution.neg_log_prob(
                x, head_pre)
        return total_recon / self.n_samples + kl

    def reconstruct(self, params, x):
        """Encode to the mean, decode, return reconstruction mean."""
        mean, _ = self._encode(params, x)
        head_pre = self._decode(params, mean)
        return self.reconstruction_distribution.sample_mean(head_pre, self.n_in)

    def generate(self, params, z):
        head_pre = self._decode(params, z)
        return self.reconstruction_distribution.sample_mean(head_pre, self.n_in)
