"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference impls: nn/layers/normalization/BatchNormalization.java (+ the cuDNN helper
CudnnBatchNormalizationHelper.java:45) and LocalResponseNormalization.java (+ cuDNN
LRN helper). On TPU both are plain fused elementwise/reduction XLA graphs; running
stats live in the layer *state* pytree (not params) and are updated functionally.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayer, Layer
from deeplearning4j_tpu.utils.serde import register_serializable


@dataclass
class _FeatureAffineNorm(BaseLayer):
    """Shared base for feature-axis normalizers with learned gamma/beta:
    nIn inference (channels for conv inputs, size otherwise), shape
    passthrough, and the never-weight-decayed convention (reference:
    BatchNormalization.java:70-76 calcL1/calcL2 -> 0)."""

    n_out: int = 0
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0

    DEFAULT_ACTIVATION = "identity"

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_out == 0:
            if input_type.kind == "convolutional":
                self.n_out = input_type.channels
            else:
                self.n_out = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def param_order(self):
        return ["gamma", "beta"]

    def regularization(self, params: dict):
        return 0.0  # gamma/beta never decayed

    def regularization_grad(self, params: dict) -> dict:
        return {}  # mirrors regularization() == 0

    def init_params(self, rng, dtype=jnp.float32):
        return {"gamma": jnp.full((self.n_out,), self.gamma_init, dtype),
                "beta": jnp.full((self.n_out,), self.beta_init, dtype)}


@register_serializable
@dataclass
class BatchNormalization(_FeatureAffineNorm):
    """Batch norm over the feature (last) axis; works for [B,F], [B,T,F], [B,H,W,C].

    Running-stat update matches the reference: global = decay*global + (1-decay)*batch
    (nn/layers/normalization/BatchNormalization.java). gamma/beta trainable unless
    ``lock_gamma_beta``.
    """

    decay: float = 0.9
    lock_gamma_beta: bool = False
    minibatch_stats: bool = True  # use minibatch stats in training (ref: isMinibatch)

    def param_order(self):
        return [] if self.lock_gamma_beta else ["gamma", "beta"]

    def init_params(self, rng, dtype=jnp.float32):
        if self.lock_gamma_beta:
            return {}
        return super().init_params(rng, dtype)

    def init_state(self, dtype=jnp.float32):
        return {"mean": jnp.zeros((self.n_out,), dtype),
                "var": jnp.ones((self.n_out,), dtype)}

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        axes = tuple(range(x.ndim - 1))
        if train and self.minibatch_stats:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean,
                "var": self.decay * state["var"] + (1.0 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xhat = (x - mean) * lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            xhat = xhat * params["gamma"] + params["beta"]
        return self.act()(xhat), new_state


@register_serializable
@dataclass
class LocalResponseNormalization(Layer):
    """Across-channel LRN: x / (k + alpha*sum_window(x^2))^beta over NHWC channels.

    Reference: nn/layers/normalization/LocalResponseNormalization.java with defaults
    k=2, n=5, alpha=1e-4, beta=0.75.
    """

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        half = self.n // 2
        sq = x * x
        window = (1,) * (x.ndim - 1) + (self.n,)
        strides = (1,) * x.ndim
        padding = [(0, 0)] * (x.ndim - 1) + [(half, half)]
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, padding)
        denom = (self.k + self.alpha * ssum) ** self.beta
        return x / denom, state


@register_serializable
@dataclass
class LayerNormalization(_FeatureAffineNorm):
    """Per-example normalization over the feature (last) axis with learned
    gamma/beta — no running stats, identical in train and eval.

    Beyond reference parity: the 2017-era reference has no LayerNorm (its
    normalizers are BatchNormalization.java and LRN); this layer exists so
    transformer stacks (SelfAttentionLayer + residual blocks, zoo
    TransformerLM) are buildable first-class. Shares the nIn-inference and
    never-weight-decayed gamma/beta convention with BatchNormalization via
    ``_FeatureAffineNorm``.
    """

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
        xhat = (x - mean) * lax.rsqrt(var + self.eps)
        return self.act()(xhat * params["gamma"] + params["beta"]), state
