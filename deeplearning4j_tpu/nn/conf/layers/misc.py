"""Misc layers: FrozenLayer (transfer learning), CenterLossOutputLayer.

Reference: nn/layers/FrozenLayer.java (wraps a layer, zeroes its updates) and
nn/conf/layers/CenterLossOutputLayer.java. Freezing here is functional: the network
applies jax.lax.stop_gradient to a frozen layer's params, so its gradients are
exactly zero and the updater never moves them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.layers.core import OutputLayer
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclass
class FrozenLayer(Layer):
    """Wraps any layer; its params receive zero gradient (stop_gradient)."""

    inner: Optional[Layer] = None

    def output_type(self, input_type: InputType) -> InputType:
        return self.inner.output_type(input_type)

    def set_n_in(self, input_type: InputType) -> None:
        self.inner.set_n_in(input_type)

    def param_order(self):
        return self.inner.param_order()

    def validate(self) -> None:
        self.inner.validate()

    def init_params(self, rng, dtype=jnp.float32):
        return self.inner.init_params(rng, dtype)

    def init_state(self, dtype=jnp.float32):
        return self.inner.init_state(dtype)

    def feed_forward_mask(self, mask, current_mask_state="active"):
        return self.inner.feed_forward_mask(mask, current_mask_state)

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        # inference-mode semantics for the wrapped layer (no dropout on frozen layers)
        return self.inner.forward(frozen, state, x, mask=mask, train=False, rng=rng)


@register_serializable
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (reference: CenterLossOutputLayer; Wen et al. 2016).

    loss = mcxent + (lambda/2) * ||features - center_{label}||^2. Class centers live
    in the layer *state* and are updated with an ``alpha`` moving average outside the
    gradient (matching the reference's non-gradient center update).
    """

    alpha: float = 0.05
    lambda_: float = 2e-4

    def init_state(self, dtype=jnp.float32):
        return {"centers": jnp.zeros((self.n_out, self.n_in), dtype)}

    def compute_loss_per_example(self, params, x, labels, weights=None, state=None):
        base = super().compute_loss_per_example(params, x, labels, weights)
        if state is None:
            return base
        centers = jax.lax.stop_gradient(state["centers"])  # [n_classes, n_in]
        assigned = jnp.dot(labels, centers)  # one-hot labels -> per-example center
        center_l = 0.5 * self.lambda_ * jnp.sum((x - assigned) ** 2, axis=-1)
        return base + center_l

    def update_centers(self, state, x, labels):
        """Moving-average center update: c_j += alpha * mean_{i: y_i=j}(x_i - c_j)."""
        centers = state["centers"]
        counts = jnp.maximum(jnp.sum(labels, axis=0), 1.0)[:, None]  # [n_classes, 1]
        assigned = jnp.dot(labels, centers)
        diff_sum = jnp.dot(labels.T, x - assigned)  # [n_classes, n_in]
        new_centers = centers + self.alpha * diff_sum / counts
        return {**state, "centers": new_centers}
