"""Core feed-forward layers: Dense, Output, Loss, Activation, Dropout, Embedding,
AutoEncoder.

Reference impls these replace: nn/layers/feedforward/dense/DenseLayer.java (im2col-free
XW+b), nn/layers/BaseOutputLayer.java (loss+gradient), nn/layers/feedforward/embedding/
EmbeddingLayer.java, nn/layers/feedforward/autoencoder/AutoEncoder.java. Backward
passes are jax.grad; dense matmuls hit the MXU directly via jnp.dot.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayer, FeedForwardLayer, Layer
from deeplearning4j_tpu.ops.losses import LossFunction, get_loss
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclass
class DenseLayer(FeedForwardLayer):
    """Fully-connected layer: activation(x @ W + b). W: [n_in, n_out].

    Serves int8-quantized weights when the params tree carries a
    ``W_scale`` sibling (optimize/quantize.py): the dequant is fused
    into the matmul epilogue — ``(x @ W_q.astype(x)) * scale`` — so W
    stays int8 in memory. Presence of the scale is a pytree-STRUCTURE
    property, i.e. part of the jit cache key: f32 and int8 param trees
    each trace their own program, and the f32 path is untouched."""

    QUANT_PARAMS = ("W",)

    def param_order(self):
        return ["W", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        kw, _ = jax.random.split(rng)
        W = self._init_w(kw, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def preactivate(self, params, x):
        scale = params.get("W_scale")
        if scale is None:
            return jnp.dot(x, params["W"]) + params["b"]
        out = jnp.dot(x, params["W"].astype(x.dtype)) * scale
        return out.astype(x.dtype) + params["b"]

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        return self.act()(self.preactivate(params, x)), state


@register_serializable
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (reference: nn/conf/layers/OutputLayer + BaseOutputLayer).

    The training loss is computed from this layer's *pre-activations* so fused
    softmax/sigmoid cross-entropy forms can be used.
    """

    loss: str = "mcxent"

    DEFAULT_ACTIVATION = "softmax"

    def loss_fn(self) -> LossFunction:
        return get_loss(self.loss)

    def compute_loss_per_example(self, params, x, labels, weights=None):
        pre = self.preactivate(params, x)
        return self.loss_fn().per_example(labels, pre, self.act(), weights)


@register_serializable
@dataclass
class LossLayer(BaseLayer):
    """Loss-only head, no params (reference: nn/conf/layers/LossLayer)."""

    loss: str = "mcxent"

    DEFAULT_ACTIVATION = "identity"

    def loss_fn(self) -> LossFunction:
        return get_loss(self.loss)

    def preactivate(self, params, x):
        return x

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        return self.act()(x), state

    def compute_loss_per_example(self, params, x, labels, weights=None):
        return self.loss_fn().per_example(labels, x, self.act(), weights)


@register_serializable
@dataclass
class ActivationLayer(BaseLayer):
    """Parameterless activation (reference: nn/conf/layers/ActivationLayer)."""

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        return self.act()(x), state


@register_serializable
@dataclass
class DropoutLayer(BaseLayer):
    """Standalone dropout layer (reference: nn/conf/layers/DropoutLayer)."""

    DEFAULT_ACTIVATION = "identity"

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        return self.act()(x), state


@register_serializable
@dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index lookup: int inputs [B] or [B,1] -> rows of W, plus bias.

    Reference: nn/layers/feedforward/embedding/EmbeddingLayer.java (equivalent to a
    one-hot matmul; implemented as a gather, which XLA lowers to dynamic-slice —
    efficient on TPU for inference; the backward is a scatter-add).
    """

    DEFAULT_ACTIVATION = "identity"

    def param_order(self):
        return ["W", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        kw, _ = jax.random.split(rng)
        W = self._init_w(kw, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        out = jnp.take(params["W"], idx, axis=0) + params["b"]
        return self.act()(out), state


@register_serializable
@dataclass
class AutoEncoder(FeedForwardLayer):
    """Denoising autoencoder (reference: nn/conf/layers/AutoEncoder +
    nn/layers/feedforward/autoencoder/AutoEncoder.java).

    Supervised forward acts as the encoder (Dense). Pretraining uses
    ``reconstruction_loss``: corrupt input, encode, decode with tied-ish weights
    (W^T + visible bias), score vs the clean input.
    """

    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"

    def param_order(self):
        return ["W", "b", "vb"]

    def bias_param_names(self):
        return frozenset({"b", "vb"})

    def init_params(self, rng, dtype=jnp.float32):
        kw, _ = jax.random.split(rng)
        W = self._init_w(kw, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)
        return {"W": W, "b": jnp.full((self.n_out,), self.bias_init, dtype),
                "vb": jnp.zeros((self.n_in,), dtype)}

    def preactivate(self, params, x):
        return jnp.dot(x, params["W"]) + params["b"]

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        return self.act()(self.preactivate(params, x)), state

    def reconstruction_loss_per_example(self, params, x, rng=None):
        corrupted = x
        if rng is not None and self.corruption_level > 0:
            keep = 1.0 - self.corruption_level
            m = jax.random.bernoulli(rng, keep, x.shape)
            corrupted = jnp.where(m, x, 0.0)
        hidden = self.act()(jnp.dot(corrupted, params["W"]) + params["b"])
        recon_pre = jnp.dot(hidden, params["W"].T) + params["vb"]
        return get_loss(self.loss).per_example(x, recon_pre, self.act(), None)
