"""Convolution / pooling / padding layers (NHWC, MXU-native).

Reference impls these replace: nn/layers/convolution/ConvolutionLayer.java:179-224
(im2col + gemm) and nn/layers/convolution/subsampling/SubsamplingLayer.java, plus the
cuDNN helpers (deeplearning4j-cuda CudnnConvolutionHelper.java:54,
CudnnSubsamplingHelper.java:49). On TPU there is no im2col and no helper SPI: convs
lower straight to `lax.conv_general_dilated` (MXU systolic matmuls) and pooling to
`lax.reduce_window`; XLA fuses bias+activation into the conv epilogue.

ConvolutionMode semantics follow nn/conf/ConvolutionMode.java: Strict (shapes must
divide exactly), Truncate (floor), Same (auto-pad, ceil(in/stride)).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayer, Layer
from deeplearning4j_tpu.utils.serde import register_serializable


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def conv_out_size(size: int, k: int, s: int, p: int, mode: str) -> int:
    if mode == "same":
        return int(math.ceil(size / s))
    out = (size - k + 2 * p) // s + 1
    if out <= 0:
        # reference: ConvolutionUtils.getOutputSize throws
        # DL4JInvalidInputException for input smaller than the kernel
        raise ValueError(
            f"Invalid configuration or input: input size {size} with "
            f"kernel {k}, stride {s}, padding {p} gives non-positive "
            f"output size {out} — input is smaller than the (padded) "
            "kernel")
    if mode == "strict" and (size - k + 2 * p) % s != 0:
        raise ValueError(
            f"ConvolutionMode.Strict: (in={size} - k={k} + 2*p={p}) not divisible by "
            f"stride {s}; use mode='truncate' or 'same'")
    return out


def validate_conv_geometry(layer, kind: str) -> None:
    """Kernel/stride/padding sanity shared by conv and pooling configs
    (reference: ConvolutionUtils + the invalid kernel/stride/padding cases
    of exceptions/TestInvalidConfigurations.java:337-380)."""
    label = getattr(layer, "name", None) or type(layer).__name__
    kh, kw = layer.kernel_size
    sh, sw = layer.stride
    ph, pw = layer.padding
    if kh <= 0 or kw <= 0:
        raise ValueError(f"Invalid {kind} configuration for layer "
                         f"'{label}': kernel {layer.kernel_size} must be "
                         "positive")
    if sh <= 0 or sw <= 0:
        raise ValueError(f"Invalid {kind} configuration for layer "
                         f"'{label}': stride {layer.stride} must be "
                         "positive")
    if ph < 0 or pw < 0:
        raise ValueError(f"Invalid {kind} configuration for layer "
                         f"'{label}': padding {layer.padding} must be "
                         "non-negative")


def _conv_padding(mode: str, pad):
    if mode == "same":
        return "SAME"
    ph, pw = _pair(pad)
    return [(ph, ph), (pw, pw)]


@register_serializable
@dataclass
class ConvolutionLayer(BaseLayer):
    """2-D convolution. Kernel [kh, kw, c_in, c_out] (HWIO); arrays NHWC."""

    n_in: int = 0   # input channels (auto-set from InputType)
    n_out: int = 0  # output channels
    kernel_size: tuple = (5, 5)
    stride: tuple = (1, 1)
    padding: tuple = (0, 0)
    convolution_mode: str = "truncate"  # strict | truncate | same
    dilation: tuple = (1, 1)

    INPUT_KIND = "cnn"
    DEFAULT_ACTIVATION = "identity"
    QUANT_PARAMS = ("W",)

    def __post_init__(self):
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)
        self.dilation = _pair(self.dilation)

    def validate(self) -> None:
        validate_conv_geometry(self, "convolution")
        super().validate()

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            if input_type.kind not in ("convolutional", "convolutional_flat"):
                raise ValueError(f"ConvolutionLayer expects CNN input, got {input_type}")
            self.n_in = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        h = conv_out_size(input_type.height, kh, sh, ph, self.convolution_mode)
        w = conv_out_size(input_type.width, kw, sw, pw, self.convolution_mode)
        return InputType.convolutional(h, w, self.n_out)

    def param_order(self):
        return ["W", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        kw_key, _ = jax.random.split(rng)
        W = self._init_w(kw_key, (kh, kw, self.n_in, self.n_out), fan_in, fan_out, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def preactivate(self, params, x):
        # int8-quantized kernels (optimize/quantize.py) carry a W_scale
        # sibling: widen on the fly and fold the per-output-channel
        # dequant into the conv epilogue ([c_out] broadcasts over NHWC)
        scale = params.get("W_scale")
        W = params["W"] if scale is None else params["W"].astype(x.dtype)
        out = lax.conv_general_dilated(
            x, W,
            window_strides=self.stride,
            padding=_conv_padding(self.convolution_mode, self.padding),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if scale is not None:
            out = (out * scale).astype(x.dtype)
        return out + params["b"]

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        return self.act()(self.preactivate(params, x)), state


@register_serializable
@dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution (fractionally-strided)."""

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        if self.convolution_mode == "same":
            h, w = input_type.height * sh, input_type.width * sw
        else:
            h = sh * (input_type.height - 1) + kh - 2 * ph
            w = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional(h, w, self.n_out)

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        kw_key, _ = jax.random.split(rng)
        W = self._init_w(kw_key, (kh, kw, self.n_in, self.n_out), fan_in, fan_out, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def preactivate(self, params, x):
        if self.convolution_mode == "same":
            padding = "SAME"
        else:
            ph, pw = self.padding
            kh, kw = self.kernel_size
            padding = [(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)]
        scale = params.get("W_scale")
        W = params["W"] if scale is None else params["W"].astype(x.dtype)
        out = lax.conv_transpose(
            x, W, strides=self.stride, padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if scale is not None:
            out = (out * scale).astype(x.dtype)
        return out + params["b"]


@register_serializable
@dataclass
class SeparableConvolution2D(ConvolutionLayer):
    """Depthwise + pointwise convolution."""

    depth_multiplier: int = 1

    QUANT_PARAMS = ("dW", "pW")

    def param_order(self):
        return ["dW", "pW", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        k1, k2, _ = jax.random.split(rng, 3)
        mid = self.n_in * self.depth_multiplier
        dW = self._init_w(k1, (kh, kw, 1, mid), kh * kw, kh * kw * self.depth_multiplier,
                          dtype)
        pW = self._init_w(k2, (1, 1, mid, self.n_out), mid, self.n_out, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"dW": dW, "pW": pW, "b": b}

    def preactivate(self, params, x):
        # per-channel dequant does not commute through the pointwise
        # mix, so each stage applies its own scale right after its conv
        dscale = params.get("dW_scale")
        dW = params["dW"] if dscale is None else params["dW"].astype(x.dtype)
        depthwise = lax.conv_general_dilated(
            x, dW, window_strides=self.stride,
            padding=_conv_padding(self.convolution_mode, self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in)
        if dscale is not None:
            depthwise = (depthwise * dscale).astype(x.dtype)
        pscale = params.get("pW_scale")
        pW = params["pW"] if pscale is None else params["pW"].astype(x.dtype)
        pointwise = lax.conv_general_dilated(
            depthwise, pW, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if pscale is not None:
            pointwise = (pointwise * pscale).astype(x.dtype)
        return pointwise + params["b"]


@register_serializable
@dataclass
class Convolution1DLayer(BaseLayer):
    """1-D (temporal) convolution over [batch, time, features]."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: int = 5
    stride: int = 1
    padding: int = 0
    convolution_mode: str = "same"

    INPUT_KIND = "rnn"
    DEFAULT_ACTIVATION = "identity"

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeseries_length
        if t is not None:
            t = conv_out_size(t, self.kernel_size, self.stride, self.padding,
                              self.convolution_mode)
        return InputType.recurrent(self.n_out, t)

    def param_order(self):
        return ["W", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        k = self.kernel_size
        kw_key, _ = jax.random.split(rng)
        W = self._init_w(kw_key, (k, self.n_in, self.n_out), self.n_in * k,
                         self.n_out * k, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        if self.convolution_mode == "same":
            padding = "SAME"
        else:
            padding = [(self.padding, self.padding)]
        out = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=padding,
            dimension_numbers=("NWC", "WIO", "NWC")) + params["b"]
        return self.act()(out), state


@register_serializable
@dataclass
class SubsamplingLayer(Layer):
    """2-D pooling: MAX / AVG / SUM / PNORM via lax.reduce_window."""

    pooling_type: str = "max"
    kernel_size: tuple = (2, 2)
    stride: tuple = (2, 2)
    padding: tuple = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    INPUT_KIND = "cnn"

    def __post_init__(self):
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)

    def validate(self) -> None:
        validate_conv_geometry(self, "subsampling")
        super().validate()

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        h = conv_out_size(input_type.height, kh, sh, ph, self.convolution_mode)
        w = conv_out_size(input_type.width, kw, sw, pw, self.convolution_mode)
        return InputType.convolutional(h, w, input_type.channels)

    def _window_padding(self):
        if self.convolution_mode == "same":
            return "SAME"
        ph, pw = self.padding
        return [(0, 0), (ph, ph), (pw, pw), (0, 0)]

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        # NOTE(perf, measured): a reshape+max fast path for kernel==stride
        # pooling was tried and REVERTED — on v5e the reshape backward
        # (broadcast-compare over the windowed view) measured 5.45 ms
        # fwd+bwd vs 4.36 ms for reduce_window's select_and_scatter at
        # [256,56,56,64] 2x2/2. XLA's lowering is already the right one.
        x = self.apply_input_dropout(x, train=train, rng=rng)
        kh, kw = self.kernel_size
        sh, sw = self.stride
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        padding = self._window_padding()
        pt = self.pooling_type.lower()
        if pt == "max":
            out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)
        elif pt in ("avg", "sum"):
            out = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            if pt == "avg":
                ones = jnp.ones_like(x)
                counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
                out = out / counts
        elif pt == "pnorm":
            p = float(self.pnorm)
            out = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides,
                                    padding) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return out, state


@register_serializable
@dataclass
class Subsampling1DLayer(Layer):
    """1-D pooling over [batch, time, features]."""

    pooling_type: str = "max"
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = "truncate"

    INPUT_KIND = "rnn"

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeseries_length
        if t is not None:
            t = conv_out_size(t, self.kernel_size, self.stride, self.padding,
                              self.convolution_mode)
        return InputType.recurrent(input_type.size, t)

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        window = (1, self.kernel_size, 1)
        strides = (1, self.stride, 1)
        if self.convolution_mode == "same":
            padding = "SAME"
        else:
            padding = [(0, 0), (self.padding, self.padding), (0, 0)]
        if self.pooling_type.lower() == "max":
            out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)
        else:
            out = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            if self.pooling_type.lower() == "avg":
                counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                           strides, padding)
                out = out / counts
        return out, state


@register_serializable
@dataclass
class ZeroPaddingLayer(Layer):
    """Spatial zero padding [(top, bottom), (left, right)]."""

    pad_top: int = 0
    pad_bottom: int = 0
    pad_left: int = 0
    pad_right: int = 0

    INPUT_KIND = "cnn"

    @staticmethod
    def of(pad):
        if isinstance(pad, int):
            return ZeroPaddingLayer(pad_top=pad, pad_bottom=pad, pad_left=pad,
                                    pad_right=pad)
        if len(pad) == 2:
            return ZeroPaddingLayer(pad_top=pad[0], pad_bottom=pad[0],
                                    pad_left=pad[1], pad_right=pad[1])
        return ZeroPaddingLayer(pad_top=pad[0], pad_bottom=pad[1], pad_left=pad[2],
                                pad_right=pad[3])

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(
            input_type.height + self.pad_top + self.pad_bottom,
            input_type.width + self.pad_left + self.pad_right,
            input_type.channels)

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        out = jnp.pad(x, ((0, 0), (self.pad_top, self.pad_bottom),
                          (self.pad_left, self.pad_right), (0, 0)))
        return out, state


@register_serializable
@dataclass
class Upsampling2D(Layer):
    """Nearest-neighbour upsampling by integer factor."""

    size: int = 2

    INPUT_KIND = "cnn"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(input_type.height * self.size,
                                       input_type.width * self.size,
                                       input_type.channels)

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        out = jnp.repeat(jnp.repeat(x, self.size, axis=1), self.size, axis=2)
        return out, state
