"""Recurrent layers: LSTM, GravesLSTM (peepholes), bidirectional, SimpleRnn,
RnnOutputLayer.

Reference impl replaced: nn/layers/recurrent/LSTMHelpers.java:172-288 (fwd) and
:368-560 (bwd) — a hand-written per-timestep Java loop shared by LSTM/GravesLSTM/
GravesBidirectionalLSTM. TPU-native design: the input projection for ALL timesteps is
one big [B*T, n_in]x[n_in, 4H] matmul (MXU-friendly), then a `lax.scan` carries
(h, c) with only the [B, H]x[H, 4H] recurrent matmul per step; the backward pass is
jax autodiff through the scan. Masking uses carry-through semantics (masked steps
propagate previous h/c), and TBPTT state carry is exposed via ``initial_state`` /
returned final state (reference: MultiLayerNetwork.java:1364 doTruncatedBPTT,
rnnTimeStep).

Data layout: [batch, time, features] (the reference uses [batch, features, time]).
Gate order in the fused 4H dimension: [i, f, o, g].
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayer, FeedForwardLayer
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.losses import get_loss
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclass
class BaseRecurrentLayer(FeedForwardLayer):
    gate_activation: str = "sigmoid"

    INPUT_KIND = "rnn"
    DEFAULT_ACTIVATION = "tanh"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = input_type.size


def _lstm_scan(x_proj, rw, c0, h0, gate_act, cell_act, mask, peepholes=None):
    """Scan an LSTM over time.

    x_proj: [B, T, 4H] precomputed input projections (+bias)
    rw:     [H, 4H] recurrent weights
    mask:   [B, T] or None
    Returns (outputs [B, T, H], (h_T, c_T)).
    """
    H = rw.shape[0]

    def step(carry, inp):
        h_prev, c_prev = carry
        if mask is not None:
            xt, mt = inp
        else:
            xt = inp
        z = xt + jnp.dot(h_prev, rw)
        zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
        if peepholes is not None:
            pi, pf, po = peepholes
            i = gate_act(zi + pi * c_prev)
            f = gate_act(zf + pf * c_prev)
            g = cell_act(zg)
            c = f * c_prev + i * g
            o = gate_act(zo + po * c)
        else:
            i = gate_act(zi)
            f = gate_act(zf)
            g = cell_act(zg)
            c = f * c_prev + i * g
            o = gate_act(zo)
        h = o * cell_act(c)
        if mask is not None:
            m = mt[:, None]
            h = m * h + (1.0 - m) * h_prev
            c = m * c + (1.0 - m) * c_prev
        return (h, c), h

    xs = jnp.swapaxes(x_proj, 0, 1)  # [T, B, 4H]
    if mask is not None:
        ms = jnp.swapaxes(mask.astype(x_proj.dtype), 0, 1)  # [T, B]
        (hT, cT), outs = lax.scan(step, (h0, c0), (xs, ms))
    else:
        (hT, cT), outs = lax.scan(step, (h0, c0), xs)
    return jnp.swapaxes(outs, 0, 1), (hT, cT)


@register_serializable
@dataclass
class LSTM(BaseRecurrentLayer):
    """Standard (peephole-free) LSTM. Params: W [n_in,4H], RW [H,4H], b [4H]."""

    forget_gate_bias_init: float = 1.0

    def param_order(self):
        return ["W", "RW", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        k1, k2 = jax.random.split(rng)
        H = self.n_out
        W = self._init_w(k1, (self.n_in, 4 * H), self.n_in, H, dtype)
        RW = self._init_w(k2, (H, 4 * H), H, H, dtype)
        b = jnp.zeros((4 * H,), dtype)
        # forget-gate bias block [H:2H] gets forget_gate_bias_init (ref: GravesLSTM
        # forgetGateBiasInit, nn/conf/layers/GravesLSTM.java)
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        return {"W": W, "RW": RW, "b": b}

    def _peepholes(self, params):
        return None

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        B = x.shape[0]
        H = self.n_out
        x_proj = jnp.dot(x, params["W"]) + params["b"]
        h0 = state.get("h", jnp.zeros((B, H), x.dtype))
        c0 = state.get("c", jnp.zeros((B, H), x.dtype))
        outs, (hT, cT) = _lstm_scan(
            x_proj, params["RW"], c0, h0,
            get_activation(self.gate_activation), self.act(), mask,
            self._peepholes(params))
        new_state = dict(state)
        new_state["h"], new_state["c"] = hT, cT
        return outs, new_state

    def step(self, params, state, x_t):
        """Single-timestep streaming inference (reference: rnnTimeStep)."""
        out, new_state = self.forward(params, state, x_t[:, None, :])
        return out[:, 0, :], new_state


@register_serializable
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013 formulation), the reference's
    workhorse RNN (nn/conf/layers/GravesLSTM.java). Adds pi/pf/po peephole params."""

    def param_order(self):
        return ["W", "RW", "b", "pi", "pf", "po"]

    def init_params(self, rng, dtype=jnp.float32):
        params = super().init_params(rng, dtype)
        H = self.n_out
        params["pi"] = jnp.zeros((H,), dtype)
        params["pf"] = jnp.zeros((H,), dtype)
        params["po"] = jnp.zeros((H,), dtype)
        return params

    def _peepholes(self, params):
        return (params["pi"], params["pf"], params["po"])


@register_serializable
@dataclass
class GravesBidirectionalLSTM(GravesLSTM):
    """Bidirectional Graves LSTM; forward+backward direction outputs are ADDED
    (reference: GravesBidirectionalLSTM via LSTMHelpers, combine mode add)."""

    def param_order(self):
        base = super().param_order()
        return [f"f_{k}" for k in base] + [f"b_{k}" for k in base]

    def bias_param_names(self):
        return frozenset({"f_b", "b_b"})

    def init_params(self, rng, dtype=jnp.float32):
        kf, kb = jax.random.split(rng)
        fwd = GravesLSTM.init_params(self, kf, dtype)
        bwd = GravesLSTM.init_params(self, kb, dtype)
        out = {f"f_{k}": v for k, v in fwd.items()}
        out.update({f"b_{k}": v for k, v in bwd.items()})
        return out

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        B, H = x.shape[0], self.n_out
        gact, cact = get_activation(self.gate_activation), self.act()

        def run(prefix, xx, mm):
            x_proj = jnp.dot(xx, params[f"{prefix}_W"]) + params[f"{prefix}_b"]
            h0 = jnp.zeros((B, H), x.dtype)
            c0 = jnp.zeros((B, H), x.dtype)
            peep = (params[f"{prefix}_pi"], params[f"{prefix}_pf"], params[f"{prefix}_po"])
            outs, _ = _lstm_scan(x_proj, params[f"{prefix}_RW"], c0, h0, gact, cact,
                                 mm, peep)
            return outs

        fwd = run("f", x, mask)
        x_rev = jnp.flip(x, axis=1)
        mask_rev = jnp.flip(mask, axis=1) if mask is not None else None
        bwd = jnp.flip(run("b", x_rev, mask_rev), axis=1)
        return fwd + bwd, state


@register_serializable
@dataclass
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} RW + b)."""

    def param_order(self):
        return ["W", "RW", "b"]

    def init_params(self, rng, dtype=jnp.float32):
        k1, k2 = jax.random.split(rng)
        H = self.n_out
        return {"W": self._init_w(k1, (self.n_in, H), self.n_in, H, dtype),
                "RW": self._init_w(k2, (H, H), H, H, dtype),
                "b": jnp.full((H,), self.bias_init, dtype)}

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        B, H = x.shape[0], self.n_out
        act = self.act()
        x_proj = jnp.dot(x, params["W"]) + params["b"]
        h0 = state.get("h", jnp.zeros((B, H), x.dtype))

        def step(h_prev, inp):
            if mask is not None:
                xt, mt = inp
            else:
                xt = inp
            h = act(xt + jnp.dot(h_prev, params["RW"]))
            if mask is not None:
                m = mt[:, None]
                h = m * h + (1.0 - m) * h_prev
            return h, h

        xs = jnp.swapaxes(x_proj, 0, 1)
        if mask is not None:
            ms = jnp.swapaxes(mask.astype(x.dtype), 0, 1)
            hT, outs = lax.scan(step, h0, (xs, ms))
        else:
            hT, outs = lax.scan(step, h0, xs)
        new_state = dict(state)
        new_state["h"] = hT
        return jnp.swapaxes(outs, 0, 1), new_state


@register_serializable
@dataclass
class RnnOutputLayer(DenseLayer):
    """Per-timestep dense + loss over [B,T,F] (reference: nn/conf/layers/
    RnnOutputLayer + nn/layers/recurrent/RnnOutputLayer.java). Label mask [B,T]
    excludes masked steps from the loss mean."""

    loss: str = "mcxent"

    INPUT_KIND = "rnn"
    DEFAULT_ACTIVATION = "softmax"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def loss_fn(self):
        return get_loss(self.loss)

    def compute_loss_per_example(self, params, x, labels, weights=None):
        pre = self.preactivate(params, x)  # [B, T, n_out]
        return self.loss_fn().per_example(labels, pre, self.act(), weights)  # [B, T]


@register_serializable
@dataclass
class LastTimeStep(BaseRecurrentLayer):
    """Select the last (unmasked) timestep: [B,T,F] -> [B,F] (reference:
    rnn/LastTimeStepVertex)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)

    def param_order(self):
        return []

    def init_params(self, rng, dtype=jnp.float32):
        return {}

    def feed_forward_mask(self, mask, current_mask_state: str = "active"):
        return None

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        if mask is None:
            return x[:, -1, :], state
        # Index of the LAST nonzero mask entry (correct for non-contiguous masks,
        # reference: rnn/LastTimeStepVertex uses the last set bit, not the count).
        T = x.shape[1]
        t_idx = jnp.arange(T, dtype=jnp.int32)[None, :]
        idx = jnp.max(jnp.where(mask > 0, t_idx, -1), axis=1)
        idx = jnp.maximum(idx, 0)  # all-masked rows fall back to step 0
        out = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]
        return out, state
