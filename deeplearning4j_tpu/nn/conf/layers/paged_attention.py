"""Paged-attention helper seam: XLA fallback + Pallas block-table kernel.

The reference ships accelerated layer math behind ``*Helper`` seams with an
always-available stock fallback (ConvolutionLayer.java:68-79 reflective
cuDNN load; helper-vs-stock parity tests under deeplearning4j-cuda/). This
module is that seam for the paged-KV decode path, the hottest serving loop
in the repo:

- :class:`XlaPagedAttention` — the stock backend. Gathers each row's block
  table into a dense ``[B, H, Tmax, d]`` view and attends; this IS the math
  that used to live inline in ``SelfAttentionLayer._paged_forward``, so it
  is bit-exact by construction and runs anywhere XLA does.
- :class:`PallasPagedAttention` — the accelerated backend. A Pallas kernel
  that walks the block table via scalar prefetch and streams K/V pages from
  the pool straight into VMEM (no materialized ``[B, H, Tmax, d]`` gather in
  HBM — the gather cost that dominates long-context decode). int8 dequant
  against the f32 ``kscales``/``vscales`` planes happens in-kernel as pages
  load; per-row ``cache_pos`` causal masking and the chunk-validity plane
  use the same expressions as the stock path, so interpret-mode output is
  bitwise identical to it (tests/test_paged_attention.py pins this).

Selection is per-platform: ``resolve_paged_backend("auto")`` picks the
kernel on TPU when :func:`supports` accepts the geometry and the stock path
everywhere else. CPU CI exercises the kernel in ``interpret=True`` mode for
parity gating only — interpret mode is not a performance path.

Only the READ side (attend over resident pages) lives behind the seam. The
write side — scattering the fresh chunk through the block table, including
the garbage-page-0 routing for masked columns — stays shared in
``_paged_forward`` so COW/prefix-sharing/snapshot semantics are identical
under every backend.

Tensor-parallel (mesh-sharded) serving hands BOTH backends a *local head
shard* of the pool instead of the full pool: ``SelfAttentionLayer`` with
``paged_mesh`` set runs the write + attend inside ``shard_map``, so
``attend`` sees ``kp``/``vp`` as ``[P, H/tp, ps, d]`` (scale planes
``[P, H/tp, ps]``) and ``q`` as ``[B, H/tp, T, d]`` with the block table
and ``cache_pos`` replicated. Neither backend needs to know: every shape
here is taken from the operands, so the XLA gather runs over the local
pool shard and the Pallas grid becomes ``(B, H/tp, NP)`` — the natural
head-axis cut of its ``(B, H, NP)`` grid. Head contexts are independent,
so per-shard outputs concatenate exactly (bit-exact at every tp).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

#: per-(b, h) program VMEM budget: two f32 [Tmax, d] K/V scratch rows plus
#: the [T, Tmax] score matrix must fit; same empirical v5e ceiling family
#: as ops/pallas_attention.supports (4096x128 compiles, 8192x128 does not)
VMEM_ROW_CEILING = 1 << 19

BACKENDS = ("xla", "pallas")
CHOICES = ("auto",) + BACKENDS


def _key_valid_plane(mask, pos, T, Tmax):
    """[B, Tmax] key validity over the cache axis for a masked chunk:
    columns belonging to this chunk take the chunk mask, everything older
    stays valid. Shared by both backends (the Pallas kernel consumes the
    plane as an input) so the masking arithmetic cannot drift."""
    colv = jnp.arange(Tmax)[None, :]
    rel = colv - pos[:, None]                                # [B, Tmax]
    chunk_valid = jnp.take_along_axis(
        mask.astype(bool), jnp.clip(rel, 0, T - 1), axis=1)
    return jnp.where((rel >= 0) & (rel < T), chunk_valid, True)


class PagedAttentionHelper:
    """One paged-attention read backend: attend a ``[B, H, T, d]`` query
    chunk over the pool pages its block table names. ``attend`` returns
    the pre-projection context ``[B, H, T, d]``; writing the fresh chunk
    into the pool is NOT the helper's job (the seam covers reads only)."""

    name = "base"

    def attend(self, q, kp, vp, bt, pos, *, mask=None,
               kscales=None, vscales=None):
        raise NotImplementedError


class XlaPagedAttention(PagedAttentionHelper):
    """Stock backend: gather-then-attend, verbatim the math that shipped
    inline in ``_paged_forward`` — the always-available fallback every
    accelerated backend must match bit-for-bit."""

    name = "xla"

    def attend(self, q, kp, vp, bt, pos, *, mask=None,
               kscales=None, vscales=None):
        B, _H, T, d = q.shape
        ps = kp.shape[2]
        NP = bt.shape[1]
        Tmax = NP * ps
        # gather each row's logical cache view:
        # [B,NP,H,ps,d] -> [B,H,Tmax,d]
        kc = kp[bt].transpose(0, 2, 1, 3, 4).reshape(B, -1, Tmax,
                                                     kp.shape[-1])
        vc = vp[bt].transpose(0, 2, 1, 3, 4).reshape(B, -1, Tmax,
                                                     vp.shape[-1])
        if kscales is not None:
            ksv = kscales[bt].transpose(0, 2, 1, 3).reshape(B, -1, Tmax)
            vsv = vscales[bt].transpose(0, 2, 1, 3).reshape(B, -1, Tmax)
            kc = kc.astype(q.dtype) * ksv[..., None].astype(q.dtype)
            vc = vc.astype(q.dtype) * vsv[..., None].astype(q.dtype)
        logits = jnp.einsum("bhtd,bhkd->bhtk", q, kc) / jnp.sqrt(
            jnp.asarray(d, q.dtype))
        col = jnp.arange(Tmax)[None, None, None, :]
        row = jnp.arange(T)[None, None, :, None]
        logits = jnp.where(col <= pos.reshape(-1, 1, 1, 1) + row,
                           logits, NEG_INF)
        if mask is not None:
            key_valid = _key_valid_plane(mask, pos, T, Tmax)
            logits = jnp.where(key_valid[:, None, None, :], logits,
                               NEG_INF)
        return jnp.einsum("bhtk,bhkd->bhtd",
                          jax.nn.softmax(logits, axis=-1), vc)


def _paged_attn_kernel(bt_ref, pos_ref, *refs, T, d, ps, NP, quant,
                       has_mask):
    """One (b, h, page) grid step. The BlockSpec index maps already
    resolved ``bt[b, i]`` through scalar prefetch, so ``kp_ref``/``vp_ref``
    hold THIS row's i-th logical page ``[ps, d]`` — the pool is never
    gathered in HBM. Pages accumulate (dequantized) into VMEM scratch;
    the final page step runs the whole attention row. The scores use the
    exact expressions of the stock path (full dot, max-subtract softmax —
    NOT the online/flash recurrence) so interpret-mode output is bitwise
    identical to :class:`XlaPagedAttention`."""
    if quant:
        if has_mask:
            (q_ref, kp_ref, vp_ref, ks_ref, vs_ref, kv_ref, o_ref,
             k_sc, v_sc) = refs
        else:
            (q_ref, kp_ref, vp_ref, ks_ref, vs_ref, o_ref,
             k_sc, v_sc) = refs
    else:
        if has_mask:
            q_ref, kp_ref, vp_ref, kv_ref, o_ref, k_sc, v_sc = refs
        else:
            q_ref, kp_ref, vp_ref, o_ref, k_sc, v_sc = refs
    b = pl.program_id(0)
    i = pl.program_id(2)
    Tmax = NP * ps
    k_pg = kp_ref[...].astype(jnp.float32)
    v_pg = vp_ref[...].astype(jnp.float32)
    if quant:
        # in-kernel dequant: int8 page values widen against the page's
        # f32 scale row as it lands in VMEM — elementwise identical to
        # the stock path's post-gather dequant
        k_pg = k_pg * ks_ref[...][:, None]
        v_pg = v_pg * vs_ref[...][:, None]
    k_sc[pl.ds(i * ps, ps), :] = k_pg
    v_sc[pl.ds(i * ps, ps), :] = v_pg

    @pl.when(i == NP - 1)
    def _attend():
        q = q_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_sc[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) / jnp.sqrt(
                jnp.asarray(d, jnp.float32))
        col = jax.lax.broadcasted_iota(jnp.int32, (T, Tmax), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (T, Tmax), 0)
        # per-row cache_pos causal mask: garbage pages (unallocated /
        # page-0 slots in the table) sit past pos+row and mask out here
        s = jnp.where(col <= pos_ref[b] + row, s, NEG_INF)
        if has_mask:
            s = jnp.where(kv_ref[...] != 0, s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        o_ref[...] = jax.lax.dot_general(
            w, v_sc[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _pallas_paged_attention(q, kp, vp, bt, pos, key_valid, kscales,
                            vscales, *, interpret):
    B, H, T, d = q.shape
    ps = kp.shape[2]
    NP = bt.shape[1]
    Tmax = NP * ps
    quant = kscales is not None
    has_mask = key_valid is not None
    kernel = functools.partial(_paged_attn_kernel, T=T, d=d, ps=ps, NP=NP,
                               quant=quant, has_mask=has_mask)
    # index maps receive (*grid, *prefetch_refs); the page maps pick pool
    # page bt[b, i] per grid step — the block-table walk lives HERE
    in_specs = [
        pl.BlockSpec((None, None, T, d),
                     lambda b, h, i, bt, pos: (b, h, 0, 0)),
        pl.BlockSpec((None, None, ps, d),
                     lambda b, h, i, bt, pos: (bt[b, i], h, 0, 0)),
        pl.BlockSpec((None, None, ps, d),
                     lambda b, h, i, bt, pos: (bt[b, i], h, 0, 0)),
    ]
    args = [q, kp, vp]
    if quant:
        in_specs += [
            pl.BlockSpec((None, None, ps),
                         lambda b, h, i, bt, pos: (bt[b, i], h, 0)),
            pl.BlockSpec((None, None, ps),
                         lambda b, h, i, bt, pos: (bt[b, i], h, 0)),
        ]
        args += [kscales, vscales]
    if has_mask:
        in_specs.append(pl.BlockSpec((None, Tmax),
                                     lambda b, h, i, bt, pos: (b, 0)))
        args.append(key_valid.astype(jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, NP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, T, d),
                               lambda b, h, i, bt, pos: (b, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((Tmax, d), jnp.float32),
                        pltpu.VMEM((Tmax, d), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, T, d), q.dtype),
        interpret=interpret,
    )(bt, pos.astype(jnp.int32), *args)


class PallasPagedAttention(PagedAttentionHelper):
    """Accelerated backend: block-table-walking Pallas kernel.

    ``interpret=None`` auto-selects interpreter mode off-TPU (the CPU CI
    parity configuration); pass ``False`` to require a real Mosaic
    compile."""

    name = "pallas"

    def __init__(self, interpret=None):
        self.interpret = interpret

    def attend(self, q, kp, vp, bt, pos, *, mask=None,
               kscales=None, vscales=None):
        interpret = self.interpret
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        T = q.shape[2]
        Tmax = bt.shape[1] * kp.shape[2]
        key_valid = None
        if mask is not None:
            # the chunk-validity plane is tiny [B, Tmax] XLA math shared
            # with the stock path; the kernel consumes it as an input
            key_valid = _key_valid_plane(mask, pos, T, Tmax)
        return _pallas_paged_attention(q, kp, vp, bt, pos, key_valid,
                                       kscales, vscales,
                                       interpret=interpret)


_HELPERS = {
    "xla": XlaPagedAttention(),
    "pallas": PallasPagedAttention(),
}


def supports(*, page_size, head_dim, n_pages, quant=False,
             platform=None):
    """Can the Pallas backend take this pool geometry on this platform?
    Used by ``auto`` selection only — a forced ``"pallas"`` knob (the CPU
    interpret-mode parity tests) bypasses it."""
    if platform is None:
        platform = jax.default_backend()
    if platform != "tpu":
        # off-TPU the kernel would run interpreted — a debugging mode,
        # never a serving win: auto falls back to stock
        return False
    # Mosaic tiling: page rows land in VMEM scratch at sublane offsets
    # i*ps, and head_dim is the lane dimension of every block
    if page_size % 8 or head_dim % 64:
        return False
    # both K and V scratch rows (and the [T, Tmax] score matrix) must
    # fit in the per-program VMEM budget
    if n_pages * page_size * head_dim > VMEM_ROW_CEILING:
        return False
    return True


def resolve_paged_backend(choice, *, page_size, head_dim, n_pages,
                          quant=False, platform=None):
    """Resolve a ``paged_attention`` knob to a concrete backend name.

    ``choice``: "auto" (Pallas on TPU when :func:`supports` accepts the
    geometry, XLA everywhere else), or a forced "xla"/"pallas". The
    result is a trace-time constant — callers key program caches on it so
    backend families never share traces. The knob must be host config,
    never data: choosing on a traced value would retrace per value (the
    graftcheck jax-retrace-hazard rule flags that pattern)."""
    if isinstance(choice, jax.core.Tracer):
        raise TypeError(
            "paged_attention backend must be static host config, got a "
            "traced value — branching on it would retrace per value")
    if choice not in CHOICES:
        raise ValueError(f"unknown paged_attention backend {choice!r} "
                         f"(expected one of {CHOICES})")
    if choice != "auto":
        return choice
    if supports(page_size=page_size, head_dim=head_dim, n_pages=n_pages,
                quant=quant, platform=platform):
        return "pallas"
    return "xla"


def get_paged_helper(backend) -> PagedAttentionHelper:
    try:
        return _HELPERS[backend]
    except KeyError:
        raise ValueError(f"unknown paged_attention backend {backend!r} "
                         f"(expected one of {BACKENDS})") from None


def paged_attend(backend, q, kp, vp, bt, pos, *, mask=None,
                 kscales=None, vscales=None):
    """Dispatch one paged-attention read through the selected backend.
    ``backend`` is a resolved name (see :func:`resolve_paged_backend`),
    static at trace time."""
    helper = get_paged_helper(backend)
    return helper.attend(q, kp, vp, bt, pos, mask=mask,
                         kscales=kscales, vscales=vscales)
