"""Mixture-of-Experts layer: top-k routed expert FFNs.

Beyond reference parity (SURVEY §2.4 taxonomy: "EP (expert parallel /
MoE): absent" in DL4J; the charter lists modern-parallelism coverage as an
idiomatic TPU extension). Design choices:

- **Dense dispatch**: every token computes through every expert and the
  top-k softmax gate weights combine them. No capacity factor, no token
  dropping, no ragged all-to-all — the einsums stay static-shaped and
  MXU-tiled, and the math EXACTLY equals ideal (infinite-capacity) sparse
  MoE routing. The FLOPs saving of sparse dispatch only pays past E~16
  experts with balanced loads; for the moderate-E regime this layer
  targets, dense is both faster on TPU and simpler to shard.
- **Expert parallelism via GSPMD**: the stacked expert params [E, ...]
  shard on their leading expert axis over the mesh model axis
  (parallel/model_sharding.py recognises this layer) — each device owns
  E/m experts, XLA partitions the expert einsums and inserts the combine
  reduction over ICI. Sharded == single-device, parity-tested.
- **load_balance_coef** is a UNIFORM-ROUTING PULL, not the Switch-style
  batch auxiliary: it penalizes the gate weights' L2 norm, nudging
  routing toward uniform when the data gives no signal. The Switch
  auxiliary (gate-probability x realized usage fraction) needs batch
  statistics from inside forward, which the per-layer loss plumbing does
  not carry — a deliberate scope cut, stated here so nobody mistakes the
  knob for collapse protection. Dense dispatch makes collapse benign for
  correctness (no capacity overflow), only for specialization quality.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.utils.serde import register_serializable


@register_serializable
@dataclass
class MixtureOfExpertsLayer(FeedForwardLayer):
    """y = sum_{e in topk} softmax_gate_e(x) * FFN_e(x).

    Input [B, F] or [B, T, F]; each expert is a 2-layer FFN with hidden
    width ``expert_hidden`` (defaults to 4 * n_out, the transformer
    convention)."""

    n_experts: int = 4
    top_k: int = 2
    expert_hidden: int = 0
    activation: str = "relu"
    load_balance_coef: float = 0.0

    def finalize(self, g=None) -> None:
        super().finalize(g)
        if self.expert_hidden == 0:
            self.expert_hidden = 4 * self.n_out
        if not 1 <= self.top_k <= self.n_experts:
            raise ValueError(f"top_k {self.top_k} not in [1, n_experts "
                             f"{self.n_experts}]")

    def param_order(self):
        return ("Wg", "W1", "b1", "W2", "b2")

    def init_params(self, rng, dtype=jnp.float32):
        kg, k1, k2 = jax.random.split(rng, 3)
        E, D, H, O = (self.n_experts, self.n_in, self.expert_hidden,
                      self.n_out)
        return {
            "Wg": self._init_w(kg, (D, E), D, E, dtype),
            "W1": self._init_w(k1, (E, D, H), D, H, dtype),
            "b1": jnp.zeros((E, H), dtype),
            "W2": self._init_w(k2, (E, H, O), H, O, dtype),
            "b2": jnp.zeros((E, O), dtype),
        }

    def bias_param_names(self):
        return frozenset(("b1", "b2"))

    def _gate(self, params, x):
        """[..., E] combine weights: softmax over ALL experts, then top-k
        mask + renormalize (gradients flow through the kept gates).
        Selection is by ``lax.top_k`` INDICES, not a >=threshold test, so
        exactly top_k experts are kept even under ties (uniform logits
        from a zero-padded token would otherwise keep all E)."""
        logits = jnp.einsum("...d,de->...e", x, params["Wg"])
        probs = jax.nn.softmax(logits, axis=-1)
        if self.top_k < self.n_experts:
            _, idx = jax.lax.top_k(probs, self.top_k)
            mask = jnp.sum(jax.nn.one_hot(idx, self.n_experts,
                                          dtype=probs.dtype), axis=-2)
            kept = probs * mask
            probs = kept / jnp.maximum(
                jnp.sum(kept, axis=-1, keepdims=True), 1e-9)
        return probs

    def forward(self, params, state, x, *, mask=None, train=False,
                rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        gates = self._gate(params, x)                       # [..., E]
        act = get_activation(self.activation)
        h = act(jnp.einsum("...d,edh->...eh", x, params["W1"])
                + params["b1"])
        y = jnp.einsum("...eh,eho->...eo", h, params["W2"]) + params["b2"]
        out = jnp.einsum("...e,...eo->...o", gates, y)
        return out, state

    def regularization(self, params):
        reg = super().regularization(params)
        # the Switch-style auxiliary needs gate statistics, which only
        # exist inside forward; a coefficient without batch statistics
        # reduces to an L2-like pull on the gate weights toward uniform
        # routing — documented approximation, off by default
        if self.load_balance_coef:
            reg = reg + self.load_balance_coef * jnp.sum(
                jnp.square(params["Wg"]))
        return reg

    def regularization_grad(self, params):
        out = super().regularization_grad(params)
        # closed form of the coef*sum(Wg^2) term above (no 0.5 factor,
        # unlike the base l2 form). ``params`` may be a partial (even
        # empty) subtree — layerwise pretraining passes only the
        # pretrained layer's params through add_regularization_grads.
        if self.load_balance_coef and "Wg" in params:
            g = 2.0 * self.load_balance_coef * params["Wg"]
            out["Wg"] = out.get("Wg", 0) + g
        return out
