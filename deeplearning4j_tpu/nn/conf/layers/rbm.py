"""Restricted Boltzmann Machine with CD-k pretraining.

Reference: nn/conf/layers/RBM.java (conf: hiddenUnit/visibleUnit/k/sparsity)
and nn/layers/feedforward/rbm/RBM.java (propUp :324, propDown :390,
gibbhVh :208, CD statistics in computeGradientAndScore :114-190:
wGrad = v0^T h0_prob - vk_prob^T hk_prob, hb = sum(h0_prob - hk_prob),
vb = sum(v0 - vk_prob); with sparsity != 0 the hb positive phase becomes
(sparsity - h0_prob)).

TPU design — no hand-coded gradient statistics: the CD-k update is expressed
as ``jax.grad`` of an energy *surrogate*

    e(v0, sg(h0_prob)) - e(sg(vk_prob), sg(hk_prob)),
    e(v, h) = -(sum(h * (v @ W)) + h . hb + v . vb)

where ``sg`` is ``lax.stop_gradient``. Differentiating the surrogate w.r.t.
(W, hb, vb) reproduces the reference's CD-k gradient exactly (the Gibbs
chain is constant under the gradient, as CD prescribes), so the RBM rides
the same jitted pretrain path (jax.value_and_grad + updater) as the
autoencoder/VAE layers instead of needing a second optimizer code path. The
whole k-step chain is traced into the one pretrain step program — k is
static config, so XLA sees a fixed unrolled chain of MXU matmuls.

Unit types (same subsets as the reference):
  hidden: binary | rectified | gaussian | identity
  visible: binary | gaussian | linear | identity
("softmax" units, present in the reference enum, are rejected in both —
the reference implementation throws for them in propUpDerivative too).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.utils.serde import register_serializable

_HIDDEN_UNITS = ("binary", "rectified", "gaussian", "identity")
_VISIBLE_UNITS = ("binary", "gaussian", "linear", "identity")


@register_serializable
@dataclass
class RBM(FeedForwardLayer):
    """RBM pretrain layer. Supervised forward == propUp mean (the hidden
    representation), so a pretrained RBM slots into a feed-forward stack the
    way the reference's does."""

    hidden_unit: str = "binary"
    visible_unit: str = "binary"
    k: int = 1
    sparsity: float = 0.0

    def validate(self) -> None:
        super().validate()
        if self.hidden_unit not in _HIDDEN_UNITS:
            raise ValueError(f"hidden_unit must be one of {_HIDDEN_UNITS}, "
                             f"got '{self.hidden_unit}'")
        if self.visible_unit not in _VISIBLE_UNITS:
            raise ValueError(f"visible_unit must be one of {_VISIBLE_UNITS}, "
                             f"got '{self.visible_unit}'")
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")

    def param_order(self):
        return ["W", "b", "vb"]

    def bias_param_names(self):
        return frozenset({"b", "vb"})

    def init_params(self, rng, dtype=jnp.float32):
        kw, _ = jax.random.split(rng)
        W = self._init_w(kw, (self.n_in, self.n_out), self.n_in, self.n_out,
                         dtype)
        return {"W": W, "b": jnp.full((self.n_out,), self.bias_init, dtype),
                "vb": jnp.zeros((self.n_in,), dtype)}

    # ------------------------------------------------------------- units
    def _hidden_mean(self, pre):
        if self.hidden_unit == "binary":
            return jax.nn.sigmoid(pre)
        if self.hidden_unit == "rectified":
            return jax.nn.relu(pre)
        return pre  # gaussian, identity: mean is the preactivation

    def _hidden_sample(self, rng, pre, mean):
        if self.hidden_unit == "binary":
            return jax.random.bernoulli(rng, mean).astype(mean.dtype)
        if self.hidden_unit == "rectified":
            # NReLU (Nair & Hinton 2010, the reference's RECTIFIED case):
            # max(0, pre + N(0,1) * sqrt(sigmoid(pre)))
            noise = jax.random.normal(rng, pre.shape, pre.dtype)
            return jax.nn.relu(pre + noise * jnp.sqrt(jax.nn.sigmoid(pre)))
        if self.hidden_unit == "gaussian":
            return mean + jax.random.normal(rng, pre.shape, pre.dtype)
        return mean  # identity: deterministic

    def _visible_mean(self, pre):
        if self.visible_unit == "binary":
            return jax.nn.sigmoid(pre)
        return pre  # gaussian, linear, identity

    # --------------------------------------------------------- propagation
    def prop_up(self, params, v):
        """Hidden mean given visible (reference propUp :324)."""
        return self._hidden_mean(jnp.dot(v, params["W"]) + params["b"])

    def prop_down(self, params, h):
        """Visible mean given hidden (reference propDown :390)."""
        return self._visible_mean(jnp.dot(h, params["W"].T) + params["vb"])

    def forward(self, params, state, x, *, mask=None, train=False, rng=None):
        x = self.apply_input_dropout(x, train=train, rng=rng)
        return self.prop_up(params, x), state

    # ------------------------------------------------------------ CD-k
    def _gibbs_chain(self, params, v0, rng):
        """k alternating Gibbs steps. Returns (h0_prob, vk_prob, hk_prob).
        Chain advances on hidden *samples*; statistics use *probabilities*
        (reference computeGradientAndScore :119-165)."""
        h0_prob = self.prop_up(params, v0)
        pre0 = jnp.dot(v0, params["W"]) + params["b"]
        h_sample = self._hidden_sample(jax.random.fold_in(rng, 0), pre0,
                                       h0_prob)
        v_prob = h_prob = None
        for i in range(self.k):
            v_prob = self.prop_down(params, h_sample)
            pre = jnp.dot(v_prob, params["W"]) + params["b"]
            h_prob = self._hidden_mean(pre)
            if i + 1 < self.k:
                h_sample = self._hidden_sample(
                    jax.random.fold_in(rng, i + 1), pre, h_prob)
        return h0_prob, v_prob, h_prob

    def pretrain_loss_per_example(self, params, x, rng):
        """Per-example CD-k surrogate whose jax.grad IS the CD-k update.

        The displayed value is the reconstruction error ||v0 - vk_prob||^2
        (monitoring-friendly, like the reference's setScoreWithZ), grafted
        onto the surrogate's gradient via the usual value-swap identity
        ``surrogate + sg(display - surrogate)``.
        """
        sg = jax.lax.stop_gradient
        h0_prob, vk_prob, hk_prob = self._gibbs_chain(params, x, rng)
        h0_prob, vk_prob, hk_prob = sg(h0_prob), sg(vk_prob), sg(hk_prob)

        # -(pos - neg) per statistic; gradient descent on this surrogate
        # ascends (pos - neg), matching the reference's negi() for pretrain
        w_term = (jnp.sum(hk_prob * jnp.dot(vk_prob, params["W"]), axis=-1)
                  - jnp.sum(h0_prob * jnp.dot(x, params["W"]), axis=-1))
        vb_term = jnp.dot(vk_prob - x, params["vb"])
        if self.sparsity != 0.0:
            # reference :173-175: with sparsity the whole hb gradient is
            # (sparsity - h0_prob) — the negative hb phase is dropped
            hb_term = jnp.dot(h0_prob, params["b"]) \
                - self.sparsity * jnp.sum(params["b"])
        else:
            hb_term = jnp.dot(hk_prob - h0_prob, params["b"])
        surrogate = w_term + hb_term + vb_term
        display = jnp.sum((x - vk_prob) ** 2, axis=-1)
        return surrogate + sg(display - surrogate)
