"""Layer configurations + functional implementations.

Unlike the reference — which splits ``nn/conf/layers`` (Jackson config) from
``nn/layers`` (imperative impls with hand-written ``backpropGradient``) — each layer
here is ONE dataclass carrying its hyperparameters (JSON round-trippable) and its
pure-functional ``init_params``/``forward``. Backward passes come from ``jax.grad``;
correctness is enforced by finite-difference gradient-check tests exactly as the
reference does (gradientcheck/GradientCheckUtil.java:41-80).
"""

from deeplearning4j_tpu.nn.conf.layers.base import Layer, BaseLayer, FeedForwardLayer
from deeplearning4j_tpu.nn.conf.layers.core import (
    DenseLayer,
    OutputLayer,
    LossLayer,
    ActivationLayer,
    DropoutLayer,
    EmbeddingLayer,
    AutoEncoder,
)
from deeplearning4j_tpu.nn.conf.layers.convolution import (
    ConvolutionLayer,
    Convolution1DLayer,
    SubsamplingLayer,
    Subsampling1DLayer,
    ZeroPaddingLayer,
    SeparableConvolution2D,
    Upsampling2D,
    Deconvolution2D,
)
from deeplearning4j_tpu.nn.conf.layers.normalization import (
    BatchNormalization,
    LayerNormalization,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.conf.layers.pooling import GlobalPoolingLayer, PoolingType
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    LSTM,
    GravesLSTM,
    GravesBidirectionalLSTM,
    RnnOutputLayer,
    SimpleRnn,
    LastTimeStep,
)
from deeplearning4j_tpu.nn.conf.layers.variational import (
    VariationalAutoencoder,
    GaussianReconstructionDistribution,
    BernoulliReconstructionDistribution,
    ExponentialReconstructionDistribution,
    CompositeReconstructionDistribution,
    LossFunctionWrapper,
)
from deeplearning4j_tpu.nn.conf.layers.misc import (
    FrozenLayer,
    CenterLossOutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.rbm import RBM
from deeplearning4j_tpu.nn.conf.layers.moe import MixtureOfExpertsLayer
from deeplearning4j_tpu.nn.conf.layers.attention import (
    PositionalEncodingLayer,
    SelfAttentionLayer,
)
