"""NeuralNetConfiguration / MultiLayerConfiguration builders.

Reference: nn/conf/NeuralNetConfiguration.java:517 (Builder), :703 (.list()),
nn/conf/MultiLayerConfiguration.java. The fluent API shape matches the reference —
global hyperparameters, then ``.list(...layers)``, then ``.input_type(...)`` and
``.build()`` performs nIn inference + automatic preprocessor insertion (reference:
InputType shape inference + InputPreProcessor auto-insertion).

JSON round-trip: ``MultiLayerConfiguration.to_json()``/``from_json`` (reference:
Jackson polymorphic JSON; our tags use ``@class`` via utils/serde.py).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    FeedForwardToCnnPreProcessor,
    InputPreProcessor,
)
from deeplearning4j_tpu.nn.updater import Sgd, Updater, get_updater
from deeplearning4j_tpu.nn.weights import Distribution
from deeplearning4j_tpu.utils import serde
from deeplearning4j_tpu.utils.serde import register_serializable


def default_preprocessor(input_type: InputType, layer: Layer) -> Optional[InputPreProcessor]:
    """Choose the preprocessor the reference would auto-insert for this transition."""
    kind_in = input_type.kind
    expects = getattr(layer, "INPUT_KIND", "any")
    if expects == "cnn":
        if kind_in in ("convolutional_flat", "feed_forward"):
            if input_type.height and input_type.width:
                return FeedForwardToCnnPreProcessor(
                    height=input_type.height, width=input_type.width,
                    channels=input_type.channels)
            raise ValueError(
                f"Cannot feed {kind_in} input into CNN layer {layer} without "
                "height/width info; use InputType.convolutional_flat(h, w, c)")
        return None
    if expects == "rnn":
        if kind_in == "convolutional":
            return CnnToRnnPreProcessor(height=input_type.height,
                                        width=input_type.width,
                                        channels=input_type.channels)
        return None
    if expects == "ff":
        if kind_in == "convolutional":
            return CnnToFeedForwardPreProcessor(height=input_type.height,
                                                width=input_type.width,
                                                channels=input_type.channels)
        return None
    return None


@register_serializable
@dataclass
class MultiLayerConfiguration:
    """Finalised sequential-network config (reference: MultiLayerConfiguration.java).

    After ``build()``: every layer's None hyperparameters are resolved, nIn fields
    are set, and ``preprocessors[i]`` holds the shape adapter applied before layer i.
    """

    layers: list = field(default_factory=list)
    preprocessors: dict = field(default_factory=dict)  # {int: InputPreProcessor}
    input_type: Optional[InputType] = None
    seed: int = 0
    updater: Updater = field(default_factory=lambda: Sgd(learning_rate=0.1))
    backprop_type: str = "standard"  # standard | tbptt
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    pretrain: bool = False
    dtype: str = "float32"
    # mixed precision: cast params+activations to this dtype inside the
    # training loss (bfloat16 puts convs/matmuls on the MXU's fast path);
    # None = compute in ``dtype``. The loss head always runs in ``dtype``.
    compute_dtype: Optional[str] = None
    # per-layer input types computed at build time (after preprocessor)
    layer_input_types: list = field(default_factory=list)

    def __post_init__(self):
        if (self.backprop_type == "tbptt"
                and self.tbptt_back_length != self.tbptt_fwd_length):
            # Reference semantics (MultiLayerNetwork.java:1364-1430) segment by the
            # fwd length and truncate within-segment backprop at the back length;
            # we support the fwd==back case (by far the common one) and reject the
            # rest explicitly rather than silently ignoring back_length. Lives here
            # (not only in the builder) so deserialized configs are covered too.
            raise ValueError(
                "tbptt_back_length != tbptt_fwd_length is not supported; "
                f"got fwd={self.tbptt_fwd_length}, back={self.tbptt_back_length}")

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        conf = serde.from_json(s)
        # JSON object keys are strings; restore int keys for preprocessors
        conf.preprocessors = {int(k): v for k, v in conf.preprocessors.items()}
        return conf

    def n_layers(self) -> int:
        return len(self.layers)


class ListBuilder:
    """Builder for MultiLayerConfiguration (reference: NeuralNetConfiguration
    .Builder.list() -> ListBuilder)."""

    def __init__(self, global_conf: "NeuralNetConfiguration", layers):
        self._g = global_conf
        self._layers = list(layers)
        self._input_type: Optional[InputType] = None
        self._preprocessors: dict[int, InputPreProcessor] = {}
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._pretrain = False

    def layer(self, layer: Layer, index: Optional[int] = None) -> "ListBuilder":
        if index is None:
            self._layers.append(layer)
        else:
            while len(self._layers) <= index:
                self._layers.append(None)
            self._layers[index] = layer
        return self

    def set_input_type(self, input_type: InputType) -> "ListBuilder":
        self._input_type = input_type
        return self

    input_type = set_input_type

    def input_pre_processor(self, index: int, p: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[index] = p
        return self

    def backprop_type(self, t: str, fwd_length: int = 20, back_length: int = 20
                      ) -> "ListBuilder":
        self._backprop_type = t
        self._tbptt_fwd = fwd_length
        self._tbptt_back = back_length
        return self

    def t_bptt_lengths(self, fwd: int, back: Optional[int] = None) -> "ListBuilder":
        return self.backprop_type("tbptt", fwd, back if back is not None else fwd)

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def build(self) -> MultiLayerConfiguration:
        g = self._g
        layers = [copy.deepcopy(l) for l in self._layers]
        if any(l is None for l in layers):
            raise ValueError("Gap in layer list (a .layer(index=...) was skipped)")
        preprocessors = dict(self._preprocessors)
        layer_input_types: list = []
        cur = self._input_type
        for i, layer in enumerate(layers):
            layer.finalize(g)
            if cur is not None:
                if i not in preprocessors:
                    auto = default_preprocessor(cur, layer)
                    if auto is not None:
                        preprocessors[i] = auto
                if i in preprocessors:
                    cur = preprocessors[i].output_type(cur)
                layer_input_types.append(cur)
                layer.set_n_in(cur)
                layer.validate()
                cur = layer.output_type(cur)
            else:
                layer_input_types.append(None)
                layer.validate()
        return MultiLayerConfiguration(
            layers=layers,
            preprocessors=preprocessors,
            input_type=self._input_type,
            seed=g.seed,
            updater=copy.deepcopy(g.updater),
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            pretrain=self._pretrain,
            dtype=g.dtype,
            compute_dtype=g.compute_dtype,
        )


@register_serializable
@dataclass
class NeuralNetConfiguration:
    """Global hyperparameter container + fluent builder entry point."""

    seed: int = 12345
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[Distribution] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None
    updater: Updater = field(default_factory=lambda: Sgd(learning_rate=0.1))
    dtype: str = "float32"
    compute_dtype: Optional[str] = None
    optimization_algo: str = "stochastic_gradient_descent"

    @staticmethod
    def builder() -> "NeuralNetConfigurationBuilder":
        return NeuralNetConfigurationBuilder()


class NeuralNetConfigurationBuilder:
    def __init__(self):
        self._c = NeuralNetConfiguration()

    def seed(self, s: int):
        self._c.seed = int(s)
        return self

    def activation(self, a: str):
        self._c.activation = str(a).lower()
        return self

    def weight_init(self, w: str, dist: Optional[Distribution] = None):
        self._c.weight_init = str(w).lower()
        if dist is not None:
            self._c.dist = dist
        return self

    def dist(self, d: Distribution):
        self._c.dist = d
        self._c.weight_init = "distribution"
        return self

    def bias_init(self, b: float):
        self._c.bias_init = float(b)
        return self

    def l1(self, v: float):
        self._c.l1 = float(v)
        return self

    def l2(self, v: float):
        self._c.l2 = float(v)
        return self

    def dropout(self, v: float):
        self._c.dropout = float(v)
        return self

    def gradient_normalization(self, mode: str):
        from deeplearning4j_tpu.nn.gradient_normalization import MODES
        m = str(mode).lower()
        if m not in MODES:
            raise ValueError(f"Unknown gradient_normalization '{mode}'; "
                             f"choose one of {MODES}")
        self._c.gradient_normalization = m
        return self

    def gradient_normalization_threshold(self, t: float):
        self._c.gradient_normalization_threshold = float(t)
        return self

    def updater(self, u, learning_rate: Optional[float] = None):
        self._c.updater = get_updater(u, learning_rate)
        return self

    def learning_rate(self, lr: float):
        self._c.updater.learning_rate = float(lr)
        return self

    def dtype(self, dt: str):
        self._c.dtype = dt
        return self

    def compute_dtype(self, dt: Optional[str]):
        self._c.compute_dtype = dt
        return self

    def optimization_algo(self, algo: str):
        self._c.optimization_algo = algo
        return self

    def list(self, *layers) -> ListBuilder:
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)):
            layers = tuple(layers[0])
        return ListBuilder(self._c, layers)

    def graph_builder(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
        return GraphBuilder(self._c)

    def build(self) -> NeuralNetConfiguration:
        return self._c
