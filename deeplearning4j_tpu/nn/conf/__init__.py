"""Configuration system (reference: deeplearning4j-nn nn/conf/)."""

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.builders import (
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
