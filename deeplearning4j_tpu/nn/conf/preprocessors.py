"""Input preprocessors: shape adapters auto-inserted between layer kinds.

Reference: nn/conf/preprocessor/* (CnnToFeedForward, FeedForwardToCnn, RnnToFeedForward,
FeedForwardToRnn, CnnToRnn, RnnToCnn). With autodiff, only the forward reshape is
needed — jax derives the backward reshape. Layouts: NHWC, [B,T,F].
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.utils.serde import register_serializable


@dataclass
class InputPreProcessor:
    def forward(self, x):
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def feed_forward_mask(self, mask):
        return mask


@register_serializable
@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B,H,W,C] -> [B, H*W*C]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x):
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.flat_size())


@register_serializable
@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """[B, H*W*C] -> [B,H,W,C]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_serializable
@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B,T,F] -> [B*T,F] (reference reshapes 3d->2d for dense layers; our dense
    layers broadcast over time natively, so this is only used when explicitly set)."""

    def forward(self, x):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)


@register_serializable
@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[B*T,F] -> [B,T,F]. Needs the time length at call sites; with static shapes we
    instead expand a plain [B,F] to [B,1,F]."""

    def forward(self, x):
        return x[:, None, :]

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size())


@register_serializable
@dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[B*T,H,W,C] is not expressible with static batch; the supported form is
    [B,H,W,C] -> [B, H*W (time), C (features)] — per-row sequence (reference uses it
    for video/frame data with explicit shapes)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x):
        b, h, w, c = x.shape
        return x.reshape(b, h * w, c)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.channels,
                                   input_type.height * input_type.width)


@register_serializable
@dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    """[B,T,F] -> [B,H,W,C] with T = H*W, F = C."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)
