"""Input preprocessors: shape adapters auto-inserted between layer kinds,
plus the statistical preprocessors (normalizers / binomial sampling).

Reference: nn/conf/preprocessor/* — all 12: the 6 shape adapters
(CnnToFeedForward, FeedForwardToCnn, RnnToFeedForward, FeedForwardToRnn,
CnnToRnn, RnnToCnn), the 3 per-batch normalizers (ZeroMeanPrePreProcessor,
UnitVarianceProcessor, ZeroMeanAndUnitVariancePreProcessor), stochastic
BinomialSamplingPreProcessor, ComposableInputPreProcessor, and the Base
abstract (here ``InputPreProcessor``). With autodiff, only the forward is
needed — jax derives the backward reshape; the normalizers stop_gradient
their batch statistics to match the reference's pass-through ``backprop``
(BaseInputPreProcessor subclasses return the epsilon unchanged). Layouts:
NHWC, [B,T,F].
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.utils.serde import register_serializable

_EPS = 1e-5  # Nd4j.EPS_THRESHOLD analog for the variance normalizers


def preprocessor_key(rng):
    """Derive the key a stochastic preprocessor may consume from a key that
    is ALSO driving the layer behind it. A preprocessor must never draw
    with its layer's own key (the same uniforms would couple e.g. the
    binarization to the dropout mask) — every call site that holds one key
    for both derives the preprocessor's via this single fold so paths that
    must agree (a vertex's forward and the graph's loss-input collection)
    stay bit-identical."""
    return None if rng is None else jax.random.fold_in(rng, 0x9E37)


@dataclass
class InputPreProcessor:
    def forward(self, x, rng=None):
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def feed_forward_mask(self, mask):
        return mask


@register_serializable
@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B,H,W,C] -> [B, H*W*C]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x, rng=None):
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.flat_size())


@register_serializable
@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """[B, H*W*C] -> [B,H,W,C]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x, rng=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_serializable
@dataclass
class ZeroMeanPrePreProcessor(InputPreProcessor):
    """Subtract per-column batch means (reference:
    ZeroMeanPrePreProcessor.java; backprop there is pass-through, so the
    statistics are constants — stop_gradient reproduces that exactly)."""

    def forward(self, x, rng=None):
        return x - jax.lax.stop_gradient(jnp.mean(x, axis=0, keepdims=True))

    def output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_serializable
@dataclass
class UnitVarianceProcessor(InputPreProcessor):
    """Divide by per-column batch std + eps (reference:
    UnitVarianceProcessor.java:40-44)."""

    def forward(self, x, rng=None):
        if x.shape[0] < 2:
            return x  # ddof=1 std is undefined (0/0) for a single example
        std = jnp.std(x, axis=0, keepdims=True, ddof=1) + _EPS
        return x / jax.lax.stop_gradient(std)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_serializable
@dataclass
class ZeroMeanAndUnitVariancePreProcessor(InputPreProcessor):
    """Standardize per column over the batch (reference:
    ZeroMeanAndUnitVariancePreProcessor.java:39-45)."""

    def forward(self, x, rng=None):
        mean = jnp.mean(x, axis=0, keepdims=True)
        x = x - jax.lax.stop_gradient(mean)
        if x.shape[0] < 2:
            return x  # ddof=1 std is undefined (0/0) for a single example
        std = jnp.std(x, axis=0, keepdims=True, ddof=1) + _EPS
        return x / jax.lax.stop_gradient(std)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_serializable
@dataclass
class BinomialSamplingPreProcessor(InputPreProcessor):
    """Bernoulli-sample activations as probabilities (reference:
    BinomialSamplingPreProcessor.java:37-39 — the RBM-stack binarizer;
    backprop there is pass-through == the straight-through estimator here).

    The reference draws from a global RNG; here sampling is deterministic
    per ``seed`` (functional purity — the same jitted program must be
    replayable), which also makes it testable.
    """

    seed: int = 0

    def forward(self, x, rng=None):
        # training passes the per-step rng (fresh samples each step, like
        # the reference's global RNG); without one, fall back to a
        # deterministic per-seed key (pure inference/replay)
        key = jax.random.PRNGKey(self.seed) if rng is None else rng
        sample = jax.random.bernoulli(key, x).astype(x.dtype)
        return x + jax.lax.stop_gradient(sample - x)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_serializable
@dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    """Chain preprocessors in order (reference:
    ComposableInputPreProcessor.java — preProcess applies in order,
    backprop in reverse; autodiff gives the reverse order for free)."""

    processors: list = field(default_factory=list)

    def forward(self, x, rng=None):
        for i, p in enumerate(self.processors):
            x = p.forward(x, rng=None if rng is None
                          else jax.random.fold_in(rng, i))
        return x

    def output_type(self, input_type: InputType) -> InputType:
        for p in self.processors:
            input_type = p.output_type(input_type)
        return input_type

    def feed_forward_mask(self, mask):
        for p in self.processors:
            mask = p.feed_forward_mask(mask)
        return mask


@register_serializable
@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B,T,F] -> [B*T,F] (reference reshapes 3d->2d for dense layers; our dense
    layers broadcast over time natively, so this is only used when explicitly set)."""

    def forward(self, x, rng=None):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)


@register_serializable
@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[B*T,F] -> [B,T,F]. Needs the time length at call sites; with static shapes we
    instead expand a plain [B,F] to [B,1,F]."""

    def forward(self, x, rng=None):
        return x[:, None, :]

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size())


@register_serializable
@dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[B*T,H,W,C] is not expressible with static batch; the supported form is
    [B,H,W,C] -> [B, H*W (time), C (features)] — per-row sequence (reference uses it
    for video/frame data with explicit shapes)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x, rng=None):
        b, h, w, c = x.shape
        return x.reshape(b, h * w, c)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.channels,
                                   input_type.height * input_type.width)


@register_serializable
@dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    """[B,T,F] -> [B,H,W,C] with T = H*W, F = C."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def forward(self, x, rng=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)
