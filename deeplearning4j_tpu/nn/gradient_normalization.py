"""Gradient normalization / clipping, applied between backprop and the
updater.

Reference: nn/conf/GradientNormalization.java (the 5-mode enum) applied in
nn/updater/BaseMultiLayerUpdater.java preApply :310-352 — per layer, over
that layer's full gradient view ("per layer") or over each named parameter
array ("per param type"). Pretrain steps skip normalization (preApply
:313).

TPU design: one pure function over the gradient pytree, traced into the
same jitted train step as backprop and the updater — the norms fuse into
the update program instead of being a separate host-side pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MODES = ("none", "renormalize_l2_per_layer", "renormalize_l2_per_param_type",
         "clip_element_wise_absolute_value", "clip_l2_per_layer",
         "clip_l2_per_param_type")

# Guards division by an exactly-zero norm (all-zero gradients). The
# reference divides unguarded and would produce inf; an eps floor keeps the
# step finite without changing any non-degenerate result.
_EPS = 1e-30


def _global_l2(g: dict):
    return jnp.sqrt(sum(jnp.sum(jnp.square(v)) for v in g.values()))


def _apply_one(mode: str, threshold: float, g: dict) -> dict:
    if mode == "renormalize_l2_per_layer":
        l2 = jnp.maximum(_global_l2(g), _EPS)
        return {k: v / l2 for k, v in g.items()}
    if mode == "renormalize_l2_per_param_type":
        return {k: v / jnp.maximum(jnp.linalg.norm(v.ravel()), _EPS)
                for k, v in g.items()}
    if mode == "clip_element_wise_absolute_value":
        return {k: jnp.clip(v, -threshold, threshold) for k, v in g.items()}
    if mode == "clip_l2_per_layer":
        l2 = _global_l2(g)
        scale = jnp.where(l2 > threshold, threshold / jnp.maximum(l2, _EPS),
                          1.0)
        return {k: v * scale.astype(v.dtype) for k, v in g.items()}
    if mode == "clip_l2_per_param_type":
        out = {}
        for k, v in g.items():
            l2 = jnp.linalg.norm(v.ravel())
            scale = jnp.where(l2 > threshold,
                              threshold / jnp.maximum(l2, _EPS), 1.0)
            out[k] = v * scale.astype(v.dtype)
        return out
    raise ValueError(f"Unknown gradient_normalization '{mode}'; "
                     f"choose one of {MODES}")


def apply_gradient_normalization(layer_map: dict, grads: dict) -> dict:
    """Apply each layer's configured mode to its gradient sub-tree.

    ``layer_map``: {key: layer config} with keys matching the gradient
    pytree's top level (layer index / vertex name). Layers with mode None
    or "none" pass through untouched. Pure and jit-traceable.
    """
    out = dict(grads)
    for key, layer in layer_map.items():
        mode = getattr(layer, "gradient_normalization", None)
        if mode is None or mode == "none" or key not in grads:
            continue
        if not grads[key]:
            continue
        threshold = getattr(layer, "gradient_normalization_threshold", None)
        threshold = 1.0 if threshold is None else float(threshold)
        out[key] = _apply_one(mode, threshold, grads[key])
    return out


def layer_map_for(net) -> dict:
    """Gradient-pytree-keyed layer map for any net exposing either a
    ``layers`` list (MultiLayerNetwork) or LayerVertex ``conf.vertices``
    (ComputationGraph) — so trainers outside the net's own step (e.g.
    ParallelWrapper) can apply the same normalization."""
    layers = getattr(net, "layers", None)
    if isinstance(layers, list):
        return {str(i): l for i, l in enumerate(layers)}
    vertices = getattr(getattr(net, "conf", None), "vertices", None)
    if isinstance(vertices, dict):
        from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
        return {name: v.layer for name, v in vertices.items()
                if isinstance(v, LayerVertex)}
    return {}
