"""ComputationGraph: DAG-network training stack.

Reference: nn/graph/ComputationGraph.java:83 (3118 LoC) — topological init
(:358,1084-1186), multi-input/output fit (:753-1030), computeGradientAndScore
(:1189-1235), vertex-map feedForward (:1247-1290).

TPU-native design mirrors MultiLayerNetwork (nn/multilayer.py): params are a
pytree ``{vertex_name: {param: Array}}``; one fit iteration — forward over the
topo-sorted DAG, summed output losses, jax.grad backward, updater — is ONE
jitted XLA program. Score is the sum of output-layer losses plus regularization
counted once (parity with ComputationGraph.java:1214-1228).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration,
    LayerVertex,
)
from deeplearning4j_tpu.nn.conf.layers.misc import CenterLossOutputLayer
from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_key
from deeplearning4j_tpu.nn.regularization import penalty_value
from deeplearning4j_tpu.nn.multilayer import _split_state
from deeplearning4j_tpu.optimize.bucketing import (BoundedCache, bucket_rows,
                                                   pad_rows)


def _as_list(x):
    if x is None:
        return None
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: dict = {}
        self.state: dict = {}
        self.updater_state: dict = {}
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        # score_value contract: array-like scalar, never guaranteed to be a
        # Python float — see MultiLayerNetwork (score() coerces)
        self.score_value = float("nan")
        # active numerical-health policy (optimize/health.py) — set by fit()
        # for its duration; see MultiLayerNetwork
        self._health = None
        self._base_key = None             # cached PRNGKey(seed), see _rng_base
        self._base_key_seed = None
        self._step_cache: dict = {}
        # inference/eval program cache: LRU-bounded, batch dim bucketed —
        # see optimize/bucketing.py
        self._output_cache = BoundedCache()
        self._rnn_state: Optional[dict] = None
        self._stream_pos = 0              # tokens consumed this stream
        self._stream_capacity = None      # min attention max_cache, if any

    # ------------------------------------------------------------------ init
    def init(self, params: Optional[dict] = None) -> "ComputationGraph":
        dtype = jnp.dtype(self.conf.dtype)
        rng = jax.random.PRNGKey(self.conf.seed)
        order = self.conf.topo_order
        keys = jax.random.split(rng, max(len(order), 1))
        if params is None:
            from deeplearning4j_tpu.utils.pytree import run_fused_on_tpu

            self.params = run_fused_on_tpu(
                lambda ks: {name: self.conf.vertices[name].init_params(
                    ks[i], dtype) for i, name in enumerate(order)}, keys)
        else:
            self.params = params
        self.state = {name: self.conf.vertices[name].init_state(dtype)
                      for name in order}
        self.updater_state = self.conf.updater.init(self.params)
        return self

    # --------------------------------------------------------------- forward
    def _forward(self, params, state, inputs, masks, *, train, rng, carry=None,
                 collect_loss_inputs=False):
        """Traverse the DAG in topo order.

        inputs/masks: lists parallel to conf.network_inputs. Returns
        (outputs list, new_states, new_carry, output_masks list, loss_inputs)
        where loss_inputs[name] is the post-preprocessor input to each output
        LayerVertex (what its loss head consumes).
        """
        conf = self.conf
        acts: dict = {k: v for k, v in zip(conf.network_inputs, inputs)}
        act_masks: dict = {k: m for k, m in zip(conf.network_inputs,
                                                masks or [None] * len(inputs))}
        ctx = {"input_arrays": dict(acts), "input_masks": dict(act_masks)}
        new_states: dict = {}
        new_carry: dict = {}
        loss_inputs: dict = {}
        if rng is not None:
            keys = jax.random.split(rng, max(len(conf.topo_order), 1))
        for i, name in enumerate(conf.topo_order):
            v = conf.vertices[name]
            v_in = [acts[k] for k in conf.vertex_inputs[name]]
            v_masks = [act_masks.get(k) for k in conf.vertex_inputs[name]]
            vertex_state = dict(state.get(name, {}))
            if carry is not None and name in carry:
                vertex_state.update(carry[name])
            k = keys[i] if rng is not None else None
            if (collect_loss_inputs and name in conf.network_outputs
                    and isinstance(v, LayerVertex)
                    and hasattr(v.layer, "compute_loss_per_example")):
                x = v_in[0]
                if v.preprocessor is not None:
                    # same derived key as LayerVertex.forward uses, so this
                    # collected loss input is bit-identical to the vertex's
                    # own activation even for stochastic preprocessors
                    x = v.preprocessor.forward(x, rng=preprocessor_key(k))
                loss_inputs[name] = x
            out, ns = v.forward(params.get(name, {}), vertex_state, v_in,
                                masks=v_masks, ctx=ctx, train=train, rng=k)
            persistent, rnn_carry = _split_state(ns)
            new_states[name] = persistent
            if rnn_carry:
                new_carry[name] = rnn_carry
            acts[name] = out
            act_masks[name] = v.feed_forward_mask(v_masks)
        outs = [acts[o] for o in conf.network_outputs]
        out_masks = [act_masks.get(o) for o in conf.network_outputs]
        return outs, new_states, new_carry, out_masks, loss_inputs

    def feed_forward(self, *inputs, train: bool = False):
        """All vertex activations as {name: array} (reference:
        ComputationGraph.feedForward :1247-1290)."""
        conf = self.conf
        acts = {k: jnp.asarray(v) for k, v in zip(conf.network_inputs, inputs)}
        ctx = {"input_arrays": dict(acts), "input_masks": {}}
        for name in conf.topo_order:
            v = conf.vertices[name]
            v_in = [acts[k] for k in conf.vertex_inputs[name]]
            out, _ = v.forward(self.params.get(name, {}),
                               self.state.get(name, {}), v_in,
                               masks=None, ctx=ctx, train=train)
            acts[name] = out
        return acts

    # ------------------------------------------------------------------ loss
    def _loss(self, params, state, x, y, input_mask, label_mask, *, train, rng,
              carry=None):
        conf = self.conf
        xs = _as_list(x)
        ys = _as_list(y)
        ims = _as_list(input_mask) or [None] * len(xs)
        lms = _as_list(label_mask) or [None] * len(ys)
        cd = getattr(conf, "compute_dtype", None)
        fwd_params = params
        if cd is not None:
            # mixed precision (see MultiLayerNetwork._loss): non-output
            # vertices compute in cd; loss heads keep the param dtype
            cdt = jnp.dtype(cd)
            outs_set = set(conf.network_outputs)
            fwd_params = {
                k: (jax.tree_util.tree_map(lambda a: a.astype(cdt), v)
                    if k not in outs_set else v)
                for k, v in params.items()}
            xs = [a.astype(cdt) for a in xs]
        _, new_states, new_carry, out_masks, loss_inputs = self._forward(
            fwd_params, state, xs, ims, train=train, rng=rng, carry=carry,
            collect_loss_inputs=True)
        if cd is not None:
            pdt = jnp.dtype(conf.dtype)
            loss_inputs = {k: v.astype(pdt) for k, v in loss_inputs.items()}
        total = 0.0
        last_in_by_out = {}
        for j, name in enumerate(conf.network_outputs):
            v = conf.vertices[name]
            if not (isinstance(v, LayerVertex)
                    and hasattr(v.layer, "compute_loss_per_example")):
                raise ValueError(f"Output vertex '{name}' has no loss head")
            last_in = loss_inputs[name]
            last_in_by_out[name] = last_in
            if isinstance(v.layer, CenterLossOutputLayer):
                per_ex = v.layer.compute_loss_per_example(
                    params[name], last_in, ys[j], state=state.get(name))
            else:
                per_ex = v.layer.compute_loss_per_example(params[name], last_in,
                                                          ys[j])
            lm = lms[j] if lms[j] is not None else out_masks[j]
            if lm is not None:
                lm = lm.reshape(per_ex.shape).astype(per_ex.dtype)
                total = total + jnp.sum(per_ex * lm) / jnp.maximum(jnp.sum(lm),
                                                                   1.0)
            else:
                total = total + jnp.mean(per_ex)
            new_states[name] = state.get(name, {})
        # penalty value reported, not differentiated — the step adds the
        # closed-form regularization_grad (see MultiLayerNetwork._loss);
        # computed fused over concatenated params, not per-tensor (480
        # micro-reductions measured 43% of the bf16 ResNet50 b128 step)
        reg = penalty_value(self, params)
        if not isinstance(reg, float):
            reg = jax.lax.stop_gradient(reg)
        return total + reg, (new_states, new_carry, last_in_by_out)

    # ------------------------------------------------------------ train step
    def _lr_mult_tree(self):
        """Per-leaf LR multipliers honoring per-layer learning_rate overrides
        (mirrors MultiLayerNetwork._lr_mult_tree)."""
        base_lr = getattr(self.conf.updater, "learning_rate", None)
        if not base_lr:
            return None
        any_override = False
        tree: dict = {}
        for name in self.conf.topo_order:
            v = self.conf.vertices[name]
            layer = v.layer if isinstance(v, LayerVertex) else None
            layer_lr = getattr(layer, "learning_rate", None)
            bias_lr = getattr(layer, "bias_learning_rate", None)
            biases = (layer.bias_param_names()
                      if layer is not None and hasattr(layer, "bias_param_names")
                      else frozenset())
            leaf = {}
            for pname in self.params.get(name, {}):
                lr = (bias_lr if (pname in biases and bias_lr is not None)
                      else layer_lr)
                leaf[pname] = (lr / base_lr) if lr is not None else 1.0
                if lr is not None:
                    any_override = True
            tree[name] = leaf
        return tree if any_override else None

    def _rng_base(self):
        """Cached base PRNG key (see MultiLayerNetwork._rng_base)."""
        if self._base_key is None or self._base_key_seed != self.conf.seed:
            self._base_key = jax.random.PRNGKey(self.conf.seed)
            self._base_key_seed = self.conf.seed
        return self._base_key

    def _make_step(self, with_carry: bool, guarded: bool = False):
        from deeplearning4j_tpu.optimize.fused_fit import build_step_core

        # shared step body — also scanned by the fused K-step driver and
        # ParallelWrapper's device round (see optimize/fused_fit.py)
        core = build_step_core(self, guarded=guarded)

        def step(params, opt_state, state, rng, iteration, xs, ys, ims, lms,
                 carry):
            return core(params, opt_state, state, rng, iteration, xs, ys,
                        ims, lms, carry if with_carry else None)

        # donated: do_step rebinds params/opt/state from the outputs
        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _get_step(self, key):
        if key not in self._step_cache:
            if key[0] == "fused":
                from deeplearning4j_tpu.optimize.fused_fit import \
                    build_fused_step
                self._step_cache[key] = build_fused_step(self,
                                                         guarded=key[-1])
            else:
                self._step_cache[key] = self._make_step(with_carry=key[-2],
                                                        guarded=key[-1])
        return self._step_cache[key]

    def do_step(self, xs, ys, input_masks=None, label_masks=None, carry=None):
        """One SGD iteration; returns (loss, new_carry)."""
        xs = [jnp.asarray(a) for a in _as_list(xs)]
        ys = [jnp.asarray(a) for a in _as_list(ys)]
        ims = ([None if m is None else jnp.asarray(m)
                for m in _as_list(input_masks)] if input_masks is not None
               else None)
        lms = ([None if m is None else jnp.asarray(m)
                for m in _as_list(label_masks)] if label_masks is not None
               else None)
        with_carry = carry is not None
        health = self._health
        guarded = health is not None
        key = (tuple(a.shape for a in xs), tuple(a.shape for a in ys),
               ims is not None and any(m is not None for m in ims),
               lms is not None and any(m is not None for m in lms), with_carry,
               guarded)
        step = self._get_step(key)
        rng = jax.random.fold_in(self._rng_base(), self.iteration)
        out = step(
            self.params, self.updater_state, self.state, rng,
            jnp.asarray(self.iteration, jnp.float32), xs, ys, ims, lms,
            carry if with_carry else {})
        if guarded:
            (self.params, self.updater_state, self.state, new_carry, loss,
             skip) = out
        else:
            self.params, self.updater_state, self.state, new_carry, loss = out
        self.iteration += 1
        # device scalar, not float(): no forced sync per step (see
        # MultiLayerNetwork.do_step)
        self.score_value = loss
        it_done = self.iteration
        if guarded:
            # observe BEFORE listener dispatch — see MultiLayerNetwork
            # .do_step: gated checkpointers need this step's skip state
            score_h, skip_h = jax.device_get((loss, skip))
            health.observe(self, score_h, skip_h, it_done - 1)
        for listener in self.listeners:
            listener.iteration_done(self, it_done)
        return self.score_value, new_carry

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs: int = 1, *,
            fused_steps: Optional[int] = None, prefetch_depth: int = 2,
            health_guard=True):
        """Train on a DataSet / MultiDataSet / iterator of either (reference:
        ComputationGraph.fit :753-1030).

        Single-input single-output DataSet streams default to the fused
        K-step fast path (see MultiLayerNetwork.fit and
        optimize/fused_fit.py); ``fused_steps=1`` opts out. MultiDataSet
        batches and TBPTT always take the per-minibatch path.

        ``health_guard`` (default ON): device-side skip of non-finite
        steps + host-side recovery ladder — see MultiLayerNetwork.fit and
        optimize/health.py. Pass ``None``/``False`` to opt out, or a
        configured ``optimize.health.HealthPolicy``."""
        from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
        from deeplearning4j_tpu.optimize.fused_fit import (FusedFitDriver,
                                                           resolve_fused_steps)
        from deeplearning4j_tpu.optimize.health import resolve_health_policy

        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        K = resolve_fused_steps(self, fused_steps)
        policy = resolve_health_policy(health_guard)
        prev_health = self._health
        if policy is not None:
            policy.bind(self)
        self._health = policy
        try:
            if isinstance(data, (DataSet, MultiDataSet)):
                if K > 1 and epochs > 1 and isinstance(data, DataSet):
                    # repeated single-batch fit: fuse the epochs loop (this
                    # path fires no epoch listeners, so semantics are
                    # unchanged)
                    FusedFitDriver(self, K, prefetch_depth).fit_stream(
                        data for _ in range(epochs))
                    return self
                for _ in range(epochs):
                    self._fit_batch(data)
                return self
            driver = (FusedFitDriver(self, K, prefetch_depth)
                      if K > 1 else None)
            for _ in range(epochs):
                for listener in self.listeners:
                    listener.on_epoch_start(self)
                if hasattr(data, "reset"):
                    data.reset()
                if driver is not None:
                    driver.fit_stream(iter(data))
                else:
                    for ds in data:
                        self._fit_batch(ds)
                for listener in self.listeners:
                    listener.on_epoch_end(self)
                self.epoch += 1
            return self
        finally:
            self._health = prev_health

    def _fit_batch(self, ds):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        if isinstance(ds, MultiDataSet):
            self.do_step(ds.features, ds.labels,
                         ds.features_masks if any(m is not None
                                                  for m in ds.features_masks)
                         else None,
                         ds.labels_masks if any(m is not None
                                                for m in ds.labels_masks)
                         else None)
            return
        if (self.conf.backprop_type == "tbptt" and ds.features.ndim == 3
                and len(self.conf.network_inputs) == 1):
            self._fit_tbptt(ds)
        else:
            self.do_step(ds.features, ds.labels, ds.features_mask,
                         ds.labels_mask)

    def _fit_tbptt(self, ds):
        """Truncated BPTT over single-input single-output rnn graphs (reference:
        ComputationGraph TBPTT path, rnnActivateUsingStoredState :1192-1200)."""
        T = ds.features.shape[1]
        L = self.conf.tbptt_fwd_length
        n_seg = max(1, math.ceil(T / L))
        carry: dict = {}
        for s in range(n_seg):
            sl = slice(s * L, min((s + 1) * L, T))
            fx = ds.features[:, sl]
            fy = ds.labels[:, sl] if ds.labels.ndim == 3 else ds.labels
            fm = ds.features_mask[:, sl] if ds.features_mask is not None else None
            lm = ds.labels_mask[:, sl] if ds.labels_mask is not None else None
            _, carry = self.do_step(fx, fy, fm, lm, carry=carry)
            carry = jax.tree_util.tree_map(jax.lax.stop_gradient, carry)

    # -------------------------------------------------------------- inference
    def _get_output(self, key, build):
        """Bounded cache for the inference/eval program family (forward,
        rnn-stream, fused-eval) — see MultiLayerNetwork._get_output."""
        if key not in self._output_cache:
            self._output_cache[key] = build()
        return self._output_cache[key]

    def output(self, *inputs, train: bool = False, masks=None):
        """Output-vertex activations; single output returns the bare array
        (reference: ComputationGraph.output). The shared batch dim is
        bucketed to the next power of two (see optimize/bucketing.py) and
        the padding stripped from every output."""
        xs = [jnp.asarray(a) for a in inputs]
        ms = ([None if m is None else jnp.asarray(m) for m in _as_list(masks)]
              if masks is not None else [None] * len(xs))
        n = xs[0].shape[0]
        B = bucket_rows(n)
        if B != n:
            xs = [pad_rows(a, B) for a in xs]
            ms = [None if m is None else pad_rows(m, B) for m in ms]
        key = (tuple(a.shape for a in xs), train,
               tuple(m is not None for m in ms))

        def build():
            def fwd(params, state, xs, ms):
                outs, _, _, _, _ = self._forward(params, state, xs, ms,
                                                 train=train, rng=None)
                return outs
            return jax.jit(fwd)

        outs = self._get_output(key, build)(self.params, self.state, xs, ms)
        if B != n:
            outs = [o[:n] for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def score(self, ds=None, x=None, y=None) -> float:
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        if ds is None and x is None:
            # coerce the device-side score_value to a host float on demand
            return float(self.score_value)
        if isinstance(ds, MultiDataSet):
            x, y = ds.features, ds.labels
            im = (ds.features_masks if any(m is not None
                                           for m in ds.features_masks) else None)
            lm = (ds.labels_masks if any(m is not None
                                         for m in ds.labels_masks) else None)
        elif ds is not None:
            x, y = ds.features, ds.labels
            im, lm = ds.features_mask, ds.labels_mask
        else:
            im = lm = None
        xs = [jnp.asarray(a) for a in _as_list(x)]
        ys = [jnp.asarray(a) for a in _as_list(y)]
        loss, _ = self._loss(
            self.params, self.state, xs, ys,
            None if im is None else [None if m is None else jnp.asarray(m)
                                     for m in _as_list(im)],
            None if lm is None else [None if m is None else jnp.asarray(m)
                                     for m in _as_list(lm)],
            train=False, rng=None)
        return float(loss)

    def evaluate(self, data, labels=None, *, top_n: int = 1, fused=None,
                 eval_batches: Optional[int] = None, prefetch_depth: int = 2):
        """Single-output classification evaluation (reference:
        ComputationGraph.evaluate). Defaults to the device-resident fused
        evaluator (evaluation/fused_eval.py — K batches per dispatch, one
        small fetch per call); pass ``fused=False`` for the per-batch
        ``output()`` + host numpy path."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.evaluation.classification import Evaluation

        ev = Evaluation(top_n=top_n)
        if labels is not None:
            data = [DataSet(np.asarray(data), np.asarray(labels))]
        elif isinstance(data, DataSet):
            data = [data]
        elif hasattr(data, "reset"):
            data.reset()
        if fused is None or fused:
            from deeplearning4j_tpu.evaluation.fused_eval import \
                FusedEvalDriver
            return FusedEvalDriver(self, eval_batches,
                                   prefetch_depth).evaluate(data, ev)
        for ds in data:
            out = self.output(ds.features, masks=ds.features_mask)
            ev.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
        return ev

    # -------------------------------------------------------- rnn streaming
    def rnn_clear_previous_state(self):
        self._rnn_state = None
        self._stream_pos = 0
        self._stream_capacity = None

    def _stream_layers(self):
        """(name, layer) pairs keyed exactly as the streaming carry dict —
        the shared vocabulary between ``_seed_streaming_carry`` and
        carry-restructuring callers (GenerationServer's paged pool)."""
        for name, v in self.conf.vertices.items():
            layer = getattr(v, "layer", None)
            if layer is not None and hasattr(layer, "init_streaming_carry"):
                yield name, layer

    def _seed_streaming_carry(self, batch: int) -> dict:
        """Initial streaming carry (attention KV caches / positional
        counters) + side effects: resets the static overflow accounting."""
        dtype = jnp.dtype(self.conf.dtype)
        seed = {}
        caps = []
        for name, layer in self._stream_layers():
            c = layer.init_streaming_carry(batch, dtype)
            if c:
                seed[name] = c
                if hasattr(layer, "max_cache"):
                    caps.append(layer.max_cache)
        self._stream_pos = 0
        self._stream_capacity = min(caps) if caps else None
        return seed

    def rnn_time_step(self, *inputs):
        """Streaming inference with persistent rnn state (reference:
        ComputationGraph.rnnTimeStep)."""
        xs = []
        squeeze = False
        for x in inputs:
            x = jnp.asarray(x)
            if x.ndim == 2:
                x = x[:, None, :]
                squeeze = True
            xs.append(x)
        if self._rnn_state is None:
            # fresh stream: seed explicit streaming caches (attention KV
            # caches / positional counters); see MultiLayerNetwork
            self._rnn_state = self._seed_streaming_carry(xs[0].shape[0])
        # static overflow accounting — under jit the layer's cache_pos is
        # a tracer and dynamic_update_slice would silently clamp
        T_in = xs[0].shape[1]
        if self._stream_capacity is not None and \
                self._stream_pos + T_in > self._stream_capacity:
            raise ValueError(
                f"KV cache overflow: stream position {self._stream_pos} + "
                f"{T_in} new tokens > max_cache {self._stream_capacity}; "
                "raise SelfAttentionLayer.max_cache or "
                "rnn_clear_previous_state()")
        self._stream_pos += T_in
        carry = self._rnn_state or {}
        # ONE jitted program per (shapes, carry structure): the eager
        # per-op dispatch path measured ~1.3 s/token through the device
        # tunnel for a 4-block transformer — ~100 round-trips per step
        key = ("rnn_stream", tuple(a.shape for a in xs),
               jax.tree_util.tree_structure(carry))

        def build():
            def fwd(params, state, xs, carry):
                outs, _, new_carry, _, _ = self._forward(
                    params, state, xs, [None] * len(xs), train=False,
                    rng=None, carry=carry)
                return outs, new_carry
            return jax.jit(fwd)

        outs, new_carry = self._get_output(key, build)(self.params,
                                                       self.state, xs, carry)
        self._rnn_state = new_carry
        outs = [o[:, 0] if squeeze and o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    # ------------------------------------------------------- params plumbing
    def params_flat(self) -> np.ndarray:
        """Contiguous param vector in (topo order, param_order) order —
        the graph analogue of MultiLayerNetwork.params()."""
        chunks = []
        for name in self.conf.topo_order:
            v = self.conf.vertices[name]
            lp = self.params.get(name, {})
            for pname in v.param_order():
                if pname in lp:
                    chunks.append(np.asarray(lp[pname]).ravel())
        if not chunks:
            return np.zeros((0,), np.float32)
        return np.concatenate(chunks)

    def set_params_flat(self, flat) -> None:
        flat = np.asarray(flat).ravel()
        off = 0
        out = {}
        for name in self.conf.topo_order:
            v = self.conf.vertices[name]
            lp = dict(self.params.get(name, {}))
            for pname in v.param_order():
                if pname in lp:
                    tmpl = lp[pname]
                    n = int(np.prod(tmpl.shape)) if tmpl.shape else 1
                    lp[pname] = jnp.asarray(
                        flat[off:off + n].reshape(tmpl.shape),
                        dtype=tmpl.dtype)
                    off += n
            out[name] = lp
        if off != flat.size:
            raise ValueError(f"Flat param size {flat.size} != expected {off}")
        self.params = out

    def num_params(self) -> int:
        return int(sum(np.prod(v.shape) for lp in self.params.values()
                       for v in lp.values()))

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    def clone(self) -> "ComputationGraph":
        import copy
        net = ComputationGraph(copy.deepcopy(self.conf))
        net.init()
        # leaf .copy(): the train step donates its input buffers, so a
        # reference-sharing clone would be invalidated by further training
        net.params = jax.tree_util.tree_map(lambda a: a.copy(), self.params)
        net.state = jax.tree_util.tree_map(lambda a: a.copy(), self.state)
        net.updater_state = jax.tree_util.tree_map(lambda a: a.copy(),
                                                   self.updater_state)
        net.iteration = self.iteration
        return net
