"""Weight initialisation schemes.

Parity with the reference's ``WeightInit`` enum + ``WeightInitUtil``
(deeplearning4j-nn/.../nn/weights/WeightInit.java, WeightInitUtil.java): XAVIER,
XAVIER_UNIFORM, XAVIER_FAN_IN, RELU, RELU_UNIFORM, UNIFORM, SIGMOID_UNIFORM,
LECUN_NORMAL/UNIFORM, ZERO, ONES, IDENTITY, DISTRIBUTION, NORMAL.

All initialisers are pure functions of a jax PRNG key.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class Distribution:
    """Config for WeightInit.DISTRIBUTION (reference: nn/conf/distribution/*)."""

    kind: str = "normal"  # normal | uniform | binomial(unsupported->normal)
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, rng, shape, dtype=jnp.float32):
        if self.kind == "uniform":
            return jax.random.uniform(rng, shape, dtype, self.lower, self.upper)
        return self.mean + self.std * jax.random.normal(rng, shape, dtype)

    def to_dict(self):
        return {"kind": self.kind, "mean": self.mean, "std": self.std,
                "lower": self.lower, "upper": self.upper}

    @staticmethod
    def from_dict(d):
        return Distribution(**d)

    @staticmethod
    def normal(mean=0.0, std=1.0):
        return Distribution(kind="normal", mean=mean, std=std)

    @staticmethod
    def uniform(lower=-1.0, upper=1.0):
        return Distribution(kind="uniform", lower=lower, upper=upper)


def init_weight(rng, shape, fan_in: float, fan_out: float, scheme: str = "xavier",
                distribution: Optional[Distribution] = None, dtype=jnp.float32):
    """Initialise a weight array. Formulas match WeightInitUtil of the reference."""
    scheme = str(scheme).lower()
    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY weight init requires square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "distribution":
        dist = distribution or Distribution()
        return dist.sample(rng, shape, dtype)
    if scheme == "normal":
        return jax.random.normal(rng, shape, dtype) / math.sqrt(max(fan_in, 1.0))
    if scheme == "lecun_normal":
        return jax.random.normal(rng, shape, dtype) * math.sqrt(1.0 / max(fan_in, 1.0))
    if scheme == "xavier":
        return jax.random.normal(rng, shape, dtype) * math.sqrt(2.0 / (fan_in + fan_out))
    if scheme == "xavier_fan_in":
        return jax.random.normal(rng, shape, dtype) / math.sqrt(max(fan_in, 1.0))
    if scheme == "xavier_legacy":
        return jax.random.normal(rng, shape, dtype) / math.sqrt(shape[0] + shape[-1])
    if scheme == "xavier_uniform":
        s = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -s, s)
    if scheme == "relu":
        return jax.random.normal(rng, shape, dtype) * math.sqrt(2.0 / max(fan_in, 1.0))
    if scheme == "relu_uniform":
        s = math.sqrt(6.0 / max(fan_in, 1.0))
        return jax.random.uniform(rng, shape, dtype, -s, s)
    if scheme == "uniform":
        a = 1.0 / math.sqrt(max(fan_in, 1.0))
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if scheme == "sigmoid_uniform":
        s = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -s, s)
    if scheme == "lecun_uniform":
        s = math.sqrt(3.0 / max(fan_in, 1.0))
        return jax.random.uniform(rng, shape, dtype, -s, s)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")
