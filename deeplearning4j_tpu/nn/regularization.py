"""Closed-form L1/L2 gradient application, shared by every gradient path.

The nets' ``_loss`` reports the penalty VALUE but stop_gradients it
(autodiff through the per-tensor reductions measured 30% of the ResNet50
train step, profiles/README.md); every consumer of ``jax.grad`` over a
net loss must therefore add the closed form ``l2*W + l1*sign(W)`` back.
This is also the reference's own architecture: DL4J applies l1/l2 inside
the updater (nn/updater/BaseUpdater postApply), not through backprop.

One helper, four call sites (MultiLayerNetwork/ComputationGraph steps,
gradient checker, solvers, ParallelWrapper) — the bug class this kills is
a fifth gradient path silently training without weight decay.
"""

from __future__ import annotations


def penalty_value(net, params):
    """The reported L1/L2 penalty VALUE (reference: computeScore adds
    fullNetworkL1 + fullNetworkL2), computed in ONE fused reduction per
    distinct (l1, l2, dtype) coefficient group over concatenated raveled
    params — NOT one reduction per tensor.

    Per-tensor reductions measured 43% of the bf16 ResNet50 b128 train
    step on a v5e (round-5 trace): ~160 param tensors x {abs-reduce,
    square-reduce, convert} is ~480 launch-overhead-bound micro-kernels
    per step, while the same math over a few concatenated vectors is a
    handful of bandwidth-bound passes. Same value (up to float reduction
    order), so score parity holds.

    Layers that override ``regularization`` beyond the BaseLayer form
    (e.g. MoE's load-balance term) keep their own (slow-path) method so
    the reported value stays exact.
    """
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers.base import BaseLayer, Layer

    def layer_param_pairs():
        layers = getattr(net, "layers", None)
        if isinstance(layers, list):
            for i, layer in enumerate(layers):
                yield layer, params.get(str(i), {})
            return
        vertices = getattr(getattr(net, "conf", None), "vertices", None)
        if isinstance(vertices, dict):
            for name, v in vertices.items():
                layer = getattr(v, "layer", None)
                yield (layer if layer is not None else v), \
                    params.get(name, {})

    groups: dict = {}  # (l1, l2, dtype) -> [raveled tensors]
    reg = 0.0
    for layer, sub in layer_param_pairs():
        if not sub:
            continue
        meth = getattr(type(layer), "regularization", None)
        if meth is None or meth is Layer.regularization:
            continue  # no penalty (base Layer / bare vertex returns 0)
        if meth is not BaseLayer.regularization:
            # custom form (MoE load-balance, BN's explicit 0) — keep exact
            reg = reg + layer.regularization(sub)
            continue
        l1 = layer.l1 or 0.0
        l2 = layer.l2 or 0.0
        l1b = layer.l1_bias or 0.0
        l2b = layer.l2_bias or 0.0
        biases = layer.bias_param_names()
        for k, v in sub.items():
            # same ``> 0`` gating as BaseLayer.regularization
            c2, c1 = (l2b, l1b) if k in biases else (l2, l1)
            c1 = c1 if c1 > 0 else 0.0
            c2 = c2 if c2 > 0 else 0.0
            if c1 == 0.0 and c2 == 0.0:
                continue
            groups.setdefault((c1, c2, v.dtype), []).append(jnp.ravel(v))
    for (c1, c2, _), vs in groups.items():
        flat = vs[0] if len(vs) == 1 else jnp.concatenate(vs)
        if c2 > 0:
            reg = reg + 0.5 * c2 * jnp.sum(flat * flat)
        if c1 > 0:
            reg = reg + c1 * jnp.sum(jnp.abs(flat))
    return reg


def add_regularization_grads(net, params, grads):
    """Return ``grads`` with each layer's analytic penalty gradient added.

    Works for MultiLayerNetwork (int-keyed layers) and ComputationGraph
    (vertex-name keys); mutates the (freshly autodiff-produced) ``grads``
    dict trees in place and returns them.
    """
    layers = getattr(net, "layers", None)
    if isinstance(layers, list):
        for i, layer in enumerate(layers):
            sub = params.get(str(i), {})
            for k, g in layer.regularization_grad(sub).items():
                grads[str(i)][k] = grads[str(i)][k] + g
        return grads
    vertices = getattr(getattr(net, "conf", None), "vertices", None)
    if isinstance(vertices, dict):
        for name, v in vertices.items():
            sub = params.get(name, {})
            for k, g in v.regularization_grad(sub).items():
                grads[name][k] = grads[name][k] + g
    return grads
