"""Closed-form L1/L2 gradient application, shared by every gradient path.

The nets' ``_loss`` reports the penalty VALUE but stop_gradients it
(autodiff through the per-tensor reductions measured 30% of the ResNet50
train step, profiles/README.md); every consumer of ``jax.grad`` over a
net loss must therefore add the closed form ``l2*W + l1*sign(W)`` back.
This is also the reference's own architecture: DL4J applies l1/l2 inside
the updater (nn/updater/BaseUpdater postApply), not through backprop.

One helper, four call sites (MultiLayerNetwork/ComputationGraph steps,
gradient checker, solvers, ParallelWrapper) — the bug class this kills is
a fifth gradient path silently training without weight decay.
"""

from __future__ import annotations


def add_regularization_grads(net, params, grads):
    """Return ``grads`` with each layer's analytic penalty gradient added.

    Works for MultiLayerNetwork (int-keyed layers) and ComputationGraph
    (vertex-name keys); mutates the (freshly autodiff-produced) ``grads``
    dict trees in place and returns them.
    """
    layers = getattr(net, "layers", None)
    if isinstance(layers, list):
        for i, layer in enumerate(layers):
            sub = params.get(str(i), {})
            for k, g in layer.regularization_grad(sub).items():
                grads[str(i)][k] = grads[str(i)][k] + g
        return grads
    vertices = getattr(getattr(net, "conf", None), "vertices", None)
    if isinstance(vertices, dict):
        for name, v in vertices.items():
            sub = params.get(name, {})
            for k, g in v.regularization_grad(sub).items():
                grads[name][k] = grads[name][k] + g
    return grads
