"""Keras HDF5 model import (reference: deeplearning4j-modelimport)."""

from deeplearning4j_tpu.modelimport.keras import (
    KerasModelImport,
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
)

__all__ = ["KerasModelImport", "import_keras_model_and_weights",
           "import_keras_sequential_model_and_weights"]
