"""Keras h5 -> native config + weights.

Reference: deeplearning4j-modelimport KerasModel.java:59,73-75 (parse the
``model_config`` JSON attribute from HDF5), :419-598 (layer-by-layer config
translation + weight copying), layers/Keras*.java translators,
preprocessors/TensorFlowCnnToFeedForwardPreProcessor.java (dim-ordering fix),
Hdf5Archive.java:46 (JavaCPP HDF5 — h5py here, no JNI).

Supported layer types (the reference's Keras-1 set, accepting Keras-2 config
spellings too): Dense, Convolution2D/Conv2D, MaxPooling2D, AveragePooling2D,
ZeroPadding2D, Flatten, Dropout, Activation, BatchNormalization, Embedding,
LSTM, GlobalAveragePooling2D/GlobalMaxPooling2D.

Layout notes (TPU-native arrays are NHWC / [B,T,F]):
- conv kernels: tf dim-ordering h5 kernels are already HWIO — copied as-is;
  th (channels_first) kernels [out, in, kh, kw] are transposed to HWIO and
  flipped (Keras-1 th performs true convolution; see
  KerasConvolution weight init in the reference).
- Flatten after conv: our CnnToFeedForwardPreProcessor flattens NHWC; a
  Dense trained against th-ordered flatten gets its rows permuted
  (reference: TensorFlowCnnToFeedForwardPreProcessor).
- LSTM gates: Keras order (i, f, c, o) -> native (i, f, o, g) block
  permutation.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.conf.layers.core import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ElementWiseVertex,
    MergeVertex,
)
from deeplearning4j_tpu.nn.conf.layers.normalization import BatchNormalization
from deeplearning4j_tpu.nn.conf.layers.pooling import GlobalPoolingLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_KERAS_ACT = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "tanh": "tanh", "softmax": "softmax", "hard_sigmoid": "hardsigmoid",
    "softplus": "softplus", "elu": "elu", "selu": "selu",
    "softsign": "softsign", "swish": "swish",
}


def _act(name):
    if name is None:
        return "identity"
    if name not in _KERAS_ACT:
        raise ValueError(f"Unsupported Keras activation '{name}'")
    return _KERAS_ACT[name]


def _cfg(layer):
    return layer.get("config", {})


class KerasLayerTranslator:
    """One Keras layer dict -> native layer config (reference: the
    KerasDense/KerasConvolution/... translator classes)."""

    def __init__(self, enforce_training_config: bool = False):
        self.enforce = enforce_training_config

    def translate(self, layer: dict, is_last: bool):
        cls = layer["class_name"]
        c = _cfg(layer)
        if cls == "Dense":
            n_out = c.get("output_dim") or c.get("units")
            act = _act(c.get("activation"))
            if is_last:
                loss = "mcxent" if act == "softmax" else "mse"
                return OutputLayer(n_out=n_out, activation=act, loss=loss)
            return DenseLayer(n_out=n_out, activation=act)
        if cls in ("Convolution2D", "Conv2D"):
            kh = c.get("nb_row") or (c.get("kernel_size") or [3, 3])[0]
            kw = c.get("nb_col") or (c.get("kernel_size") or [3, 3])[1]
            n_out = c.get("nb_filter") or c.get("filters")
            stride = tuple(c.get("subsample") or c.get("strides") or (1, 1))
            mode = ("same" if (c.get("border_mode") or c.get("padding"))
                    == "same" else "truncate")
            return ConvolutionLayer(n_out=n_out, kernel_size=(kh, kw),
                                    stride=stride, convolution_mode=mode,
                                    activation=_act(c.get("activation")))
        if cls in ("MaxPooling2D", "AveragePooling2D"):
            pool = tuple(c.get("pool_size") or (2, 2))
            stride = tuple(c.get("strides") or pool)
            return SubsamplingLayer(
                pooling_type="max" if cls.startswith("Max") else "avg",
                kernel_size=pool, stride=stride,
                convolution_mode=("same" if (c.get("border_mode")
                                             or c.get("padding")) == "same"
                                  else "truncate"))
        if cls == "ZeroPadding2D":
            p = c.get("padding") or (1, 1)
            if isinstance(p[0], (list, tuple)):
                (pt, pb), (pl, pr) = p
            else:
                pt = pb = p[0]
                pl = pr = p[1]
            return ZeroPaddingLayer(pad_top=pt, pad_bottom=pb, pad_left=pl,
                                    pad_right=pr)
        if cls == "Flatten":
            return "flatten"  # handled via preprocessor auto-insertion
        if cls == "Dropout":
            return DropoutLayer(dropout=c.get("p") or c.get("rate") or 0.5)
        if cls == "Activation":
            return ActivationLayer(activation=_act(c.get("activation")))
        if cls == "BatchNormalization":
            return BatchNormalization(eps=c.get("epsilon", 1e-5),
                                      decay=c.get("momentum", 0.9))
        if cls == "Embedding":
            return EmbeddingLayer(n_in=c.get("input_dim"),
                                  n_out=c.get("output_dim"),
                                  activation="identity")
        if cls == "LSTM":
            n_out = c.get("output_dim") or c.get("units")
            act = _act(c.get("activation"))
            gate = _act(c.get("inner_activation")
                        or c.get("recurrent_activation") or "sigmoid")
            return LSTM(n_out=n_out, activation=act, gate_activation=gate)
        if cls in ("GlobalAveragePooling2D", "GlobalMaxPooling2D"):
            return GlobalPoolingLayer(
                pooling_type="avg" if "Average" in cls else "max")
        if cls == "InputLayer":
            return None
        raise ValueError(f"Unsupported Keras layer type '{cls}'")

    def input_type(self, layer: dict, dim_ordering: str):
        """InputType from the first layer's batch_input_shape."""
        c = _cfg(layer)
        shape = c.get("batch_input_shape") or c.get("batch_shape")
        if shape is None:
            return None
        shape = [s for s in shape[1:]]  # drop batch dim
        if len(shape) == 3:
            if dim_ordering == "th":
                ch, h, w = shape
            else:
                h, w, ch = shape
            return InputType.convolutional(h, w, ch)
        if len(shape) == 2:
            return InputType.recurrent(shape[1], shape[0])
        if len(shape) == 1:
            return InputType.feed_forward(shape[0])
        return None


class KerasModelImport:
    """reference: KerasModelImport.java entry points."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str, quantize=None) -> MultiLayerNetwork:
        return import_keras_sequential_model_and_weights(
            path, quantize=quantize)

    @staticmethod
    def import_keras_model_and_weights(path: str, quantize=None):
        return import_keras_model_and_weights(path, quantize=quantize)


def _model_config(f) -> dict:
    raw = f.attrs.get("model_config")
    if raw is None:
        raise ValueError("No 'model_config' attribute in HDF5 file "
                         "(weights-only files are not importable as models)")
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8")
    return json.loads(raw)


def import_keras_model_and_weights(path: str, quantize=None):
    """Functional or Sequential model import. Sequential (and LINEAR
    functional) models become a MultiLayerNetwork; BRANCHED functional
    DAGs (residual adds, concat merges — the zoo-class models) become a
    ComputationGraph (reference: KerasModel.java:419-495 builds a
    ComputationGraphConfiguration.GraphBuilder; merge layers via
    layers/KerasMerge.java).

    ``quantize="int8"`` rewrites the imported weights to absmax
    per-channel int8 (optimize/quantize.py) before returning — the
    imported-then-quantized net serves through the same fused-dequant
    path as a quantized zoo model."""
    import h5py

    with h5py.File(path, "r") as f:
        config = _model_config(f)
    if config["class_name"] == "Sequential":
        return import_keras_sequential_model_and_weights(
            path, quantize=quantize)
    cfg = config["config"]
    layers = cfg["layers"] if isinstance(cfg, dict) else cfg
    n_outputs = (len(_layer_refs(cfg.get("output_layers", [])))
                 if isinstance(cfg, dict) else 1)
    if n_outputs <= 1 and _is_linear(layers):
        # single-output linear chains keep the (simpler, flat-indexed)
        # sequential path; a multi-OUTPUT model must stay functional even
        # when its layer chain looks linear, or intermediate outputs are
        # silently dropped. The InputLayer stays in the list — it
        # contributes no layer but carries the input shape (Keras 3 puts
        # batch_shape only there)
        fake = {"class_name": "Sequential", "config": list(layers)}
        return _maybe_quantize(_import_sequential(path, fake), quantize)
    return _maybe_quantize(_import_functional(path, config), quantize)


def _maybe_quantize(net, quantize):
    if quantize is None:
        return net
    from deeplearning4j_tpu.optimize.quantize import quantize_net
    return quantize_net(net, quantize)


def _inbound_names(layer: dict):
    """Input layer names of one functional-API layer, across config eras:
    Keras 1/2 ``[[["name", node, tensor], ...]]`` and Keras 3 legacy-h5
    ``[{"args": [__keras_tensor__...]}]`` (keras_history carries the
    producing layer name)."""
    nodes = layer.get("inbound_nodes") or []
    if not nodes:
        return []
    if len(nodes) > 1:
        raise ValueError(
            f"Layer '{layer.get('name')}' is shared (has "
            f"{len(nodes)} inbound nodes) — shared-layer reuse is not "
            "supported (the reference rejects these too)")
    node = nodes[0]
    names = []
    if isinstance(node, dict):  # Keras 3
        def collect(obj):
            if isinstance(obj, dict):
                if obj.get("class_name") == "__keras_tensor__":
                    names.append(obj["config"]["keras_history"][0])
                else:
                    for v in obj.values():
                        collect(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    collect(v)
        collect(node.get("args", []))
        collect(node.get("kwargs", {}))
    else:  # Keras 1/2: list of [name, node_index, tensor_index, (kwargs)]
        for ref in node:
            names.append(ref[0] if isinstance(ref, (list, tuple)) else ref)
    return names


def _layer_refs(v):
    """Normalize input_layers/output_layers config entries to layer
    names: ['name', 0, 0] | [['a',0,0], ['b',0,0]] | ['a', 'b']."""
    if not isinstance(v, (list, tuple)):
        return [v]
    if v and not isinstance(v[0], (list, tuple)):
        # either a single ['name', n, t] triple or a list of names
        if len(v) >= 2 and isinstance(v[1], int):
            return [v[0]]
        return list(v)
    return [r[0] if isinstance(r, (list, tuple)) else r for r in v]


def _is_linear(layers) -> bool:
    """True when every non-input layer has exactly one inbound CONNECTION
    and nothing branches (each producer feeds at most one consumer). The
    inbound names are counted WITHOUT dedup: ``Add()([x, x])`` names the
    same tensor twice, but it is still a two-input merge — deduping would
    flatten it into a linear stack and silently import x + x as x. A
    model with several InputLayers is never linear — flattening disjoint
    input chains into one stack would mis-wire them."""
    if sum(1 for l in layers if l["class_name"] == "InputLayer") > 1:
        return False
    consumers: dict = {}
    for l in layers:
        if l["class_name"] == "InputLayer":
            continue
        try:
            ins = _inbound_names(l)
        except ValueError:
            return False
        if len(ins) > 1:
            return False
        for i in ins:
            consumers[i] = consumers.get(i, 0) + 1
    return all(c <= 1 for c in consumers.values())


# Keras merge-layer class -> (vertex factory). Concatenate merges along
# the feature axis (our MergeVertex); the rest are pointwise
# (ElementWiseVertex ops) — reference: layers/KerasMerge.java
_MERGE_CLASSES = {
    "Add": lambda c: ElementWiseVertex(op="add"),
    "Subtract": lambda c: ElementWiseVertex(op="subtract"),
    "Multiply": lambda c: ElementWiseVertex(op="product"),
    "Average": lambda c: ElementWiseVertex(op="average"),
    "Maximum": lambda c: ElementWiseVertex(op="max"),
    "Concatenate": lambda c: MergeVertex(),
}
_MERGE_MODES = {  # Keras-1 Merge(mode=...)
    "sum": "Add", "mul": "Multiply", "ave": "Average", "max": "Maximum",
    "concat": "Concatenate",
}


def _inbound_rank(layer: dict):
    """Tensor rank of the layer's inputs when the config records it
    (Keras 3 keeps each __keras_tensor__'s shape); None otherwise."""
    nodes = layer.get("inbound_nodes") or []
    for node in nodes:
        if not isinstance(node, dict):
            continue
        found = []

        def collect(obj):
            if isinstance(obj, dict):
                if obj.get("class_name") == "__keras_tensor__":
                    shape = obj.get("config", {}).get("shape")
                    if shape is not None:
                        found.append(len(shape))
                else:
                    for v in obj.values():
                        collect(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    collect(v)
        collect(node.get("args", []))
        if found:
            return found[0]
    return None


def _merge_vertex(layer: dict):
    cls = layer["class_name"]
    if cls == "Merge":  # Keras 1
        mode = _cfg(layer).get("mode", "sum")
        if mode not in _MERGE_MODES:
            raise ValueError(f"Unsupported Keras-1 Merge mode '{mode}'")
        cls = _MERGE_MODES[mode]
    if cls not in _MERGE_CLASSES:
        return None
    if cls == "Concatenate":
        # only feature-axis (last-axis) merges map to MergeVertex, like
        # the reference's KerasMerge; "last axis" is rank-dependent —
        # axis=1 IS the feature axis of [B,F] Dense outputs
        axis = _cfg(layer).get("axis", -1)
        rank = _inbound_rank(layer)
        ok = (axis == -1 or (rank is not None and axis == rank - 1)
              or (rank is None and axis == 3))  # legacy NHWC assumption
        if not ok:
            raise ValueError(
                f"Concatenate axis {axis} unsupported for rank-{rank} "
                "inputs (feature-axis merge only, like the reference "
                "MergeVertex)")
    return _MERGE_CLASSES[cls](_cfg(layer))


def _import_functional(path: str, config: dict):
    """Branched functional DAG -> ComputationGraph with weights."""
    import h5py

    from deeplearning4j_tpu.nn.graph import ComputationGraph

    cfg = config["config"]
    layers = cfg["layers"]
    translator = KerasLayerTranslator()

    dim_ordering = "tf"
    for ld in layers:
        d = _cfg(ld).get("dim_ordering") or _cfg(ld).get("data_format")
        if d:
            dim_ordering = {"channels_first": "th",
                            "channels_last": "tf"}.get(d, d)
            break

    output_names = _layer_refs(cfg.get("output_layers", []))
    input_names = _layer_refs(cfg.get("input_layers", []))

    builder = (NeuralNetConfiguration.builder().seed(12345).graph_builder())
    alias: dict = {}       # dropped layer name -> upstream effective name
    keras_names: list = [] # vertex names that carry weights
    input_types: dict = {}

    def resolve(name):
        while name in alias:
            name = alias[name]
        return name

    for ld in layers:
        cls = ld["class_name"]
        name = _cfg(ld).get("name") or ld.get("name")
        ins = [resolve(n) for n in _inbound_names(ld)]
        if cls == "InputLayer":
            it = translator.input_type(ld, dim_ordering)
            builder.add_inputs(name)
            if it is not None:
                input_types[name] = it
            continue
        mv = _merge_vertex(ld)
        if mv is not None:
            builder.add_vertex(name, mv, *ins)
            continue
        t = translator.translate(ld, is_last=(name in output_names))
        if t is None or t == "flatten":
            # flatten is absorbed by the builder's automatic
            # CnnToFeedForward preprocessor on the consumer (parity:
            # KerasModel.java:487 preprocessor insertion)
            alias[name] = ins[0]
            continue
        builder.add_layer(name, t, *ins)
        keras_names.append(name)

    if not input_names:
        raise ValueError("Functional model config lists no input_layers")
    missing = [n for n in input_names if n not in input_types]
    if missing:
        raise ValueError(f"Could not infer input shape for {missing} "
                         "(no batch shape on the InputLayer)")
    builder.set_input_types(*[input_types[n] for n in input_names])
    builder.set_outputs(*[resolve(n) for n in output_names])
    conf = builder.build()
    net = ComputationGraph(conf).init()

    with h5py.File(path, "r") as f:
        for name in keras_names:
            ws = _weight_arrays(f, name)
            if not ws:
                continue
            v = conf.vertices[name]
            p = dict(net.params.get(name, {}))
            st = dict(net.state.get(name, {}))
            new_p, new_st = _layer_param_update(
                v.layer, p, st, ws, dim_ordering, v.preprocessor)
            net.params[name] = new_p
            if new_st is not None:
                net.state[name] = new_st
    return net


def import_keras_sequential_model_and_weights(
        path: str, quantize=None) -> MultiLayerNetwork:
    import h5py

    with h5py.File(path, "r") as f:
        config = _model_config(f)
    if config["class_name"] != "Sequential":
        raise ValueError("Not a Sequential model; use "
                         "import_keras_model_and_weights")
    return _maybe_quantize(_import_sequential(path, config), quantize)


def _import_sequential(path: str, config: dict) -> MultiLayerNetwork:
    import h5py

    layer_dicts = config["config"]
    if isinstance(layer_dicts, dict):  # Keras 2 nests under "layers"
        layer_dicts = layer_dicts["layers"]
    translator = KerasLayerTranslator()

    dim_ordering = "tf"
    for ld in layer_dicts:
        d = _cfg(ld).get("dim_ordering") or _cfg(ld).get("data_format")
        if d:
            dim_ordering = {"channels_first": "th",
                            "channels_last": "tf"}.get(d, d)
            break

    native_layers = []
    keras_names = []  # keras layer name per native layer (for weights)
    input_type = None
    n_real = sum(1 for l in layer_dicts
                 if l["class_name"] not in ("InputLayer", "Flatten"))
    seen_real = 0
    for i, ld in enumerate(layer_dicts):
        if input_type is None:
            it = translator.input_type(ld, dim_ordering)
            if it is not None:
                input_type = it
        t = translator.translate(
            ld, is_last=(seen_real + 1 == n_real
                         and ld["class_name"] not in ("InputLayer",
                                                      "Flatten")))
        if t is None or t == "flatten":
            continue
        seen_real += 1
        native_layers.append(t)
        keras_names.append(_cfg(ld).get("name") or ld.get("name")
                           or f"layer_{i}")

    if input_type is None:
        raise ValueError("Could not infer input shape "
                         "(no batch_input_shape in first layer)")
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .list(*native_layers)
            .set_input_type(input_type)
            .build())
    net = MultiLayerNetwork(conf).init()
    _copy_weights(path, net, keras_names, dim_ordering)
    return net


def _weight_arrays(f, keras_name: str):
    """Ordered weight arrays for one keras layer from the model_weights
    group (reference: KerasModel weight loading via 'weight_names' attr)."""
    root = f["model_weights"] if "model_weights" in f else f
    if keras_name not in root:
        return []
    g = root[keras_name]
    names = g.attrs.get("weight_names")
    out = []
    if names is not None:
        for n in names:
            n = n.decode() if isinstance(n, bytes) else str(n)
            out.append(np.asarray(g[n]))
    else:
        def visit(_, obj):
            import h5py as _h
            if isinstance(obj, _h.Dataset):
                out.append(np.asarray(obj))
        g.visititems(visit)
    return out


def _layer_param_update(layer, p, st, ws, dim_ordering, preprocessor):
    """Apply one keras layer's weight arrays ``ws`` to its native param
    dict ``p`` (+ state ``st`` for BN running stats). Shared by the
    sequential (flat-indexed) and functional (vertex-named) importers.
    Returns (new_params, new_state_or_None)."""
    new_st = None
    if isinstance(layer, ConvolutionLayer):
        k, b = ws[0], (ws[1] if len(ws) > 1 else None)
        if k.ndim == 4 and dim_ordering == "th":
            # [out, in, kh, kw] true-conv -> HWIO cross-correlation
            k = np.transpose(k, (2, 3, 1, 0))[::-1, ::-1]
        p["W"] = jnp.asarray(np.ascontiguousarray(k), p["W"].dtype)
        if b is not None:
            p["b"] = jnp.asarray(b, p["b"].dtype)
    elif isinstance(layer, (DenseLayer, OutputLayer)):
        W, b = ws[0], (ws[1] if len(ws) > 1 else None)
        if W.shape != tuple(p["W"].shape):
            raise ValueError(
                f"Dense weight shape {W.shape} != expected "
                f"{tuple(p['W'].shape)}")
        if (dim_ordering == "th" and preprocessor is not None
                and hasattr(preprocessor, "channels")):
            # keras th Flatten emitted (c,h,w) order; our flatten is
            # NHWC -> permute rows (reference:
            # TensorFlowCnnToFeedForwardPreProcessor inverse)
            h_, w_, c_ = (preprocessor.height, preprocessor.width,
                          preprocessor.channels)
            perm = np.arange(c_ * h_ * w_).reshape(
                c_, h_, w_).transpose(1, 2, 0).ravel()
            W = W[perm]
        p["W"] = jnp.asarray(W, p["W"].dtype)
        if b is not None:
            p["b"] = jnp.asarray(b, p["b"].dtype)
    elif isinstance(layer, BatchNormalization):
        # keras order: gamma, beta, moving_mean, moving_var
        for name, w in zip(["gamma", "beta"], ws[:2]):
            if name in p:
                p[name] = jnp.asarray(w, p[name].dtype)
        if len(ws) >= 4:
            new_st = dict(st)
            new_st["mean"] = jnp.asarray(ws[2])
            new_st["var"] = jnp.asarray(ws[3])
    elif isinstance(layer, LSTM):
        p.update(_lstm_weights(ws, layer, p))
    elif isinstance(layer, EmbeddingLayer):
        p["W"] = jnp.asarray(ws[0], p["W"].dtype)
    return p, new_st


def _copy_weights(path, net, keras_names, dim_ordering):
    import h5py

    with h5py.File(path, "r") as f:
        for i, (layer, kname) in enumerate(zip(net.conf.layers, keras_names)):
            ws = _weight_arrays(f, kname)
            if not ws:
                continue
            key = str(i)
            p, new_st = _layer_param_update(
                layer, dict(net.params[key]), dict(net.state.get(key, {})),
                ws, dim_ordering, net.conf.preprocessors.get(i))
            net.params[key] = p
            if new_st is not None:
                net.state[key] = new_st


def _lstm_weights(ws, layer, p):
    """Keras LSTM weights -> native {W, RW, b} with (i,f,c,o)->(i,f,o,g)
    block permutation. Handles Keras-2 packed (kernel, recurrent, bias) and
    Keras-1 per-gate 12-array layouts."""
    H = layer.n_out

    def permute(m, axis):
        blocks = np.split(m, 4, axis=axis)  # i, f, c, o
        return np.concatenate([blocks[0], blocks[1], blocks[3], blocks[2]],
                              axis=axis)

    if len(ws) == 3:
        W, RW, b = ws
        return {"W": jnp.asarray(permute(W, 1), p["W"].dtype),
                "RW": jnp.asarray(permute(RW, 1), p["RW"].dtype),
                "b": jnp.asarray(permute(b, 0), p["b"].dtype)}
    if len(ws) == 12:
        # keras1 order: W_i U_i b_i, W_c U_c b_c, W_f U_f b_f, W_o U_o b_o
        Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = ws
        W = np.concatenate([Wi, Wf, Wo, Wc], axis=1)
        RW = np.concatenate([Ui, Uf, Uo, Uc], axis=1)
        b = np.concatenate([bi, bf, bo, bc], axis=0)
        return {"W": jnp.asarray(W, p["W"].dtype),
                "RW": jnp.asarray(RW, p["RW"].dtype),
                "b": jnp.asarray(b, p["b"].dtype)}
    raise ValueError(f"Unexpected LSTM weight count {len(ws)}")
