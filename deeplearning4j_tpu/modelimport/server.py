"""Keras-backend entry point: drive training of imported Keras models from
an external process.

Reference: deeplearning4j-keras — a py4j ``GatewayServer``
(keras/Server.java:18) exposing ``DeepLearning4jEntryPoint.fit``
(keras/DeepLearning4jEntryPoint.java:12,21-33): a Python Keras process
hands over an exported model file plus minibatch files on disk, and the
JVM trains. Here the transport is stdlib HTTP+JSON (py4j is a JVM bridge;
an HTTP entry point serves any client), batches are ``.npz`` files with
``features``/``labels`` arrays (the HDF5MiniBatchDataSetIterator analog):

- POST /import   {"path": "model.h5"}                   -> {"model": id}
- POST /fit      {"model": id, "batches": [paths], "epochs": n}
- POST /evaluate {"model": id, "batches": [paths]}      -> {"accuracy": ..}
- POST /predict  {"model": id, "features": [[..], ..]}  -> {"output": ..}
- GET  /models                                          -> {"models": [..]}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


class KerasBackendServer:
    def __init__(self, port: int = 0):
        self._port = port
        self._models: dict = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    # ---------------------------------------------------------- operations
    def import_model(self, path: str) -> str:
        from deeplearning4j_tpu.modelimport.keras import (
            import_keras_model_and_weights,
        )
        net = import_keras_model_and_weights(path)
        with self._lock:
            mid = f"m{self._next_id}"
            self._next_id += 1
            self._models[mid] = net
        return mid

    def _net(self, mid: str):
        net = self._models.get(mid)
        if net is None:
            raise KeyError(f"unknown model '{mid}'")
        return net

    @staticmethod
    def _load_batches(paths):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        out = []
        for p in paths:
            with np.load(p) as z:
                out.append(DataSet(z["features"], z["labels"]))
        return out

    def fit(self, mid: str, batch_paths, epochs: int = 1) -> dict:
        # one lock serializes all model operations: ThreadingHTTPServer
        # handles requests concurrently, and two interleaved fit() calls on
        # one net would race on its iteration/score/updater state
        with self._lock:
            net = self._net(mid)
            batches = self._load_batches(batch_paths)
            for _ in range(epochs):
                for ds in batches:
                    net.fit(ds)
            return {"iterations": int(net.iteration),
                    "score": float(net.score_value)}

    def evaluate(self, mid: str, batch_paths) -> dict:
        from deeplearning4j_tpu.evaluation import Evaluation

        with self._lock:
            net = self._net(mid)
            ev = Evaluation()
            for ds in self._load_batches(batch_paths):
                ev.eval(ds.labels, np.asarray(net.output(ds.features)))
            return {"accuracy": ev.accuracy(), "f1": ev.f1()}

    def predict(self, mid: str, features) -> list:
        with self._lock:
            out = self._net(mid).output(np.asarray(features, np.float32))
        if isinstance(out, (list, tuple)):
            out = out[0]
        return np.asarray(out).tolist()

    def list_models(self) -> list:
        with self._lock:
            return sorted(self._models)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/models":
                    self._json({"models": server.list_models()})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n))
                    if self.path == "/import":
                        self._json({"model":
                                    server.import_model(req["path"])})
                    elif self.path == "/fit":
                        self._json(server.fit(req["model"], req["batches"],
                                              int(req.get("epochs", 1))))
                    elif self.path == "/evaluate":
                        self._json(server.evaluate(req["model"],
                                                   req["batches"]))
                    elif self.path == "/predict":
                        self._json({"output":
                                    server.predict(req["model"],
                                                   req["features"])})
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:  # noqa: BLE001 — report to client
                    self._json({"error": f"{type(e).__name__}: {e}"}, 400)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
