"""Keras-backend entry point: drive training of imported Keras models from
an external process.

Reference: deeplearning4j-keras — a py4j ``GatewayServer``
(keras/Server.java:18) exposing ``DeepLearning4jEntryPoint.fit``
(keras/DeepLearning4jEntryPoint.java:12,21-33): a Python Keras process
hands over an exported model file plus minibatch files on disk, and the
JVM trains. Here the transport is stdlib HTTP+JSON (py4j is a JVM bridge;
an HTTP entry point serves any client), batches are ``.npz`` files with
``features``/``labels`` arrays (the HDF5MiniBatchDataSetIterator analog):

- POST /import   {"path": "model.h5"}                   -> {"model": id}
- POST /fit      {"model": id, "batches": [paths], "epochs": n}
- POST /evaluate {"model": id, "batches": [paths]}      -> {"accuracy": ..}
- POST /predict  {"model": id, "features": [[..], ..],
                  "deadline_s": 2.0}                    -> {"output": ..}
- POST /generate {"model": id, "prompt_ids": [..], "max_tokens": n,
                  "temperature": t, "top_k": k, "seed": s,
                  "deadline_s": 2.0}                    -> {"tokens": [..]}
- POST /rag      {"model": id, "prompt_ids": [..], "max_tokens": n,
                  "query_vec": [..], "k": 4, ...}
                  -> {"tokens": [..], "docs": [..], "prefix_len": n}
- GET  /models                                          -> {"models": [..]}
- GET  /stats                                           -> serving counters
- GET  /metrics                     -> Prometheus text exposition (0.0.4)

/generate serves models registered with ``attach_generation`` through a
slot-pooled continuous-batching ``GenerationServer``
(parallel/generation.py) and maps its typed failures onto the same
taxonomy: 429 past the admission watermark, 503 while the breaker is
open, 504 when the per-request deadline expires (queued OR
mid-generation — the decode slot is freed either way).

/rag serves models registered with ``attach_rag`` through a two-tier
``RagPipeline`` (parallel/rag.py): the query retrieves top-k passages
from a knn-tier ``EmbeddingIndex``, the passages assemble into a
canonical chunk-aligned prefix (hot documents dedupe prefill through
the generate tier's prefix cache), and the generate tier completes —
one deadline budget propagated across both tiers, the same
429/503/504 typing end to end.

The serving path degrades typed instead of failing open
(parallel/resilience.py): /predict sheds load with 429 past the
``max_pending`` admission watermark, fast-fails 503 while the circuit
breaker is open, 504s requests whose ``deadline_s`` budget ran out, and
retries transient dispatch faults with backoff. Malformed JSON, unknown
model ids, and bodies beyond ``max_body_bytes`` return structured 4xx
JSON errors ({"error": ..., "type": ...}) — never a traceback-driven 500
or unbounded buffering.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.metrics.exposition import CONTENT_TYPE, render_text
from deeplearning4j_tpu.metrics.registry import (MetricsRegistry,
                                                 global_registry)
from deeplearning4j_tpu.parallel.resilience import (AdmissionController,
                                                    ChaosPolicy,
                                                    CircuitBreaker,
                                                    CircuitOpen, Deadline,
                                                    DeadlineExceeded,
                                                    ReplicaKilled,
                                                    ReplicaUnavailable,
                                                    RetryPolicy,
                                                    ServerOverloaded,
                                                    TransientDispatchError)


class UnknownModelError(KeyError):
    """Request named a model id this server never imported (HTTP 404 —
    distinct from a bare KeyError, which means a missing request field
    and maps to 400)."""


#: error type -> HTTP status for the typed serving taxonomy
_STATUS = {
    ServerOverloaded: 429,
    CircuitOpen: 503,          # incl. every fleet replica breaker open
    TransientDispatchError: 503,  # retry budget spent on transient faults
    ReplicaUnavailable: 503,   # whole fleet dead/draining/restarting
    ReplicaKilled: 503,        # replica died and the failover budget ended
    DeadlineExceeded: 504,
}


class KerasBackendServer:
    def __init__(self, port: int = 0, *, max_body_bytes: int = 64 << 20,
                 max_pending: int = 64,
                 request_deadline_s: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 chaos: Optional[ChaosPolicy] = None,
                 registry: Optional[MetricsRegistry] = None):
        """Resilience knobs mirror ``ParallelInference``: ``max_pending``
        bounds concurrent in-flight requests (beyond it /predict returns
        429 immediately), ``request_deadline_s`` is the default /predict
        budget (per-request ``deadline_s`` in the JSON body overrides; None
        = unbounded), ``retry``/``breaker`` guard the model dispatch, and
        ``chaos`` injects faults into it — test/bench only, default off.
        ``max_body_bytes`` caps request bodies (413 beyond it; the body
        is discarded unbuffered, never parsed)."""
        self._port = port
        self._models: dict = {}
        self._generators: dict = {}
        self._inference: dict = {}
        self._rags: dict = {}
        # leaf lock for the /predict server registry: predict() must not
        # touch self._lock before admission (the legacy path holds it for
        # the whole dispatch — the watermark could never 429)
        self._inference_lock = threading.Lock()
        self._next_id = 0
        self._lock = threading.Lock()
        self._httpd = None
        self._thread = None
        self.max_body_bytes = int(max_body_bytes)
        self.request_deadline_s = request_deadline_s
        self.admission = AdmissionController(max_pending)
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._chaos = chaos
        # serving counters live in the (leaf-locked) registry; GET
        # /metrics renders this registry merged with every attached
        # model's own (labeled by model id) plus any register_metrics()
        # extras and the process-global training registry
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._m_retried = self.metrics.counter(
            "server_retried_total", "dispatch retries")
        self._m_expired = self.metrics.counter(
            "server_expired_total", "requests failed on deadline")
        self._m_rejected_circuit = self.metrics.counter(
            "server_rejected_circuit_total",
            "requests shed while the breaker was open")
        self._m_completed = self.metrics.counter(
            "server_completed_total", "requests completed")
        self._m_failed = self.metrics.counter(
            "server_failed_total", "requests failed on error")
        self.metrics.gauge("server_pending",
                           "admitted-but-unresolved requests",
                           fn=lambda: self.admission.pending)
        self.metrics.gauge("server_accepted",
                           "requests accepted by admission",
                           fn=lambda: self.admission.accepted)
        self.metrics.gauge("server_rejected",
                           "requests rejected by admission",
                           fn=lambda: self.admission.rejected)
        self.metrics.gauge("server_models", "imported models",
                           fn=lambda: len(self._models))
        self._extra_metrics: list = []

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    # ---------------------------------------------------------- operations
    def import_model(self, path: str) -> str:
        from deeplearning4j_tpu.modelimport.keras import (
            import_keras_model_and_weights,
        )
        net = import_keras_model_and_weights(path)
        with self._lock:
            mid = f"m{self._next_id}"
            self._next_id += 1
            self._models[mid] = net
        return mid

    def _net(self, mid: str):
        net = self._models.get(mid)
        if net is None:
            raise UnknownModelError(f"unknown model '{mid}'")
        return net

    @staticmethod
    def _load_batches(paths):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        out = []
        for p in paths:
            with np.load(p) as z:
                out.append(DataSet(z["features"], z["labels"]))
        return out

    def fit(self, mid: str, batch_paths, epochs: int = 1) -> dict:
        # one lock serializes all model operations: ThreadingHTTPServer
        # handles requests concurrently, and two interleaved fit() calls on
        # one net would race on its iteration/score/updater state
        with self._lock:
            net = self._net(mid)
            batches = self._load_batches(batch_paths)
            for _ in range(epochs):
                for ds in batches:
                    net.fit(ds)
            return {"iterations": int(net.iteration),
                    "score": float(net.score_value)}

    def evaluate(self, mid: str, batch_paths) -> dict:
        from deeplearning4j_tpu.evaluation import Evaluation

        with self._lock:
            net = self._net(mid)
            ev = Evaluation()
            for ds in self._load_batches(batch_paths):
                ev.eval(ds.labels, np.asarray(net.output(ds.features)))
            return {"accuracy": ev.accuracy(), "f1": ev.f1()}

    def _count_retry(self, attempt, exc) -> None:
        self._m_retried.inc()

    def _check_deadline(self, deadline: Optional[Deadline], stage: str):
        if deadline is not None and deadline.expired():
            self._m_expired.inc()
            raise DeadlineExceeded(
                f"request budget exhausted {stage} "
                f"({-deadline.remaining() * 1e3:.1f} ms over)")

    def predict(self, mid: str, features,
                deadline_s: Optional[float] = None) -> list:
        """The guarded serving entry: admission -> breaker gate -> model
        lock -> dispatch under retry, with the deadline re-checked at each
        stage boundary so a request whose budget died waiting never costs
        a device program. Models registered with ``attach_inference``
        route through their coalescing server (or replica fleet) instead —
        its own admission/breaker/deadline typing maps onto the same
        429/503/504 taxonomy."""
        budget = deadline_s if deadline_s is not None \
            else self.request_deadline_s
        with self._inference_lock:
            inf = self._inference.get(mid)
        if inf is not None:
            x = np.asarray(features, np.float32)
            fut = inf.submit(x, deadline_s=budget)
            try:
                # the server resolves deadlined requests itself; the slack
                # only guards a wedged server from hanging the HTTP thread
                out = fut.result(timeout=None if budget is None
                                 else budget + 30.0)
            except Exception:
                self._m_failed.inc()
                raise
            self._m_completed.inc()
            return np.asarray(out).tolist()
        deadline = None if budget is None else Deadline(budget)
        if not self.breaker.allow():
            self._m_rejected_circuit.inc()
            raise CircuitOpen("circuit breaker is open: recent dispatches "
                              "failed above threshold")
        self.admission.acquire()  # raises ServerOverloaded at watermark
        try:
            with self._lock:
                # the model-lock wait can eat the whole budget under load
                self._check_deadline(deadline, "waiting for the model lock")
                net = self._net(mid)
            x = np.asarray(features, np.float32)
            dispatch = (self._chaos.wrap(net.output)
                        if self._chaos is not None else net.output)

            def attempt():
                # each ATTEMPT serializes under the model lock, but the
                # retry backoff sleeps happen outside it: one request's
                # retry storm must not stall every other HTTP worker
                with self._lock:
                    self._check_deadline(deadline,
                                         "waiting for the model lock")
                    try:
                        result = dispatch(x)
                    except Exception:
                        self.breaker.record_failure()
                        raise
                    self.breaker.record_success()
                    return result

            out = self.retry.call(attempt, deadline=deadline,
                                  on_retry=self._count_retry)
            self._m_completed.inc()
        except Exception:
            self._m_failed.inc()
            raise
        finally:
            self.admission.release()
        if isinstance(out, (list, tuple)):
            out = out[0]
        return np.asarray(out).tolist()

    def attach_generation(self, net, *, vocab: int, slots: int = 4,
                          eos_id: Optional[int] = None,
                          mid: Optional[str] = None, replicas: int = 1,
                          fleet_kw: Optional[dict] = None,
                          roles: Optional[Sequence[str]] = None,
                          **gen_kw) -> str:
        """Register a causal LM for /generate, served by a paged
        ``GenerationServer`` (continuous batching over a page-pool
        KV-cache — parallel/generation.py). ``net`` may be a model
        instance or an already-imported model id; returns the model id
        /generate requests should name. Extra kwargs are forwarded to
        the ``GenerationServer``: paging (``page_size``, ``pages``,
        ``prefix_cache``, ``prefill_chunk``, ``steps_per_dispatch``),
        speculative decoding (``draft_net``, ``spec_k``), and
        resilience (max_pending, request_deadline_s, retry, breaker,
        chaos, ...). Page-pool occupancy, prefix-cache reuse, COW, and
        speculation counters surface per model under ``pages`` in
        /stats.

        ``replicas > 1`` serves the model through a ``ReplicaFleet`` of
        independent GenerationServers (health-routed failover, supervised
        restart, zero lost futures across replica death — parallel/
        fleet.py); ``fleet_kw`` forwards to the fleet (hedge_after_s,
        restart_backoff_s, ...). The per-replica health/breaker/restart
        block then appears under this model in /stats.

        ``roles`` (rid-indexed, e.g. ``("prefill", "decode")``) serves
        the model through *disaggregated* tiers: each replica's
        GenerationServer is built with its declared role and the fleet
        routes fresh requests through prefill-export -> decode-adopt,
        degrading to co-located serving when the decode tier is dark.
        Requires ``replicas == len(roles) > 1``."""
        from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
        from deeplearning4j_tpu.parallel.generation import GenerationServer

        with self._lock:
            if isinstance(net, str):
                mid = net
                net = self._net(mid)
            elif mid is None:
                mid = f"m{self._next_id}"
                self._next_id += 1
            self._models[mid] = net
            old = self._generators.pop(mid, None)
        if old is not None:
            old.close()
        if roles is not None and int(replicas) <= 1:
            raise ValueError("roles= needs replicas > 1 (one server "
                             "per tier replica)")
        if int(replicas) > 1:
            def factory(rid):
                kw = dict(gen_kw)
                if roles is not None:
                    kw["role"] = roles[rid]
                return GenerationServer(net, vocab, slots=slots,
                                        eos_id=eos_id, **kw)
            fkw = dict(fleet_kw or {})
            if roles is not None:
                fkw.setdefault("roles", tuple(roles))
            gen = ReplicaFleet(factory, replicas=int(replicas), **fkw)
        else:
            gen = GenerationServer(net, vocab, slots=slots, eos_id=eos_id,
                                   **gen_kw)
        with self._lock:
            self._generators[mid] = gen
        return mid

    def attach_inference(self, net, *, mid: Optional[str] = None,
                         replicas: int = 1,
                         fleet_kw: Optional[dict] = None,
                         **inf_kw) -> str:
        """Register a model for /predict behind a coalescing
        ``ParallelInference`` server — or, with ``replicas > 1``, a
        ``ReplicaFleet`` of them — instead of the default
        lock-serialized direct dispatch. ``net`` may be a model instance
        or an imported model id; ``inf_kw`` forwards to each
        ``ParallelInference`` (max_batch, max_wait_ms, max_pending,
        chaos, ...), ``fleet_kw`` to the fleet."""
        from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        with self._lock:
            if isinstance(net, str):
                mid = net
                net = self._net(mid)
            elif mid is None:
                mid = f"m{self._next_id}"
                self._next_id += 1
            self._models[mid] = net
        with self._inference_lock:
            old = self._inference.pop(mid, None)
        if old is not None:
            old.close()
        if int(replicas) > 1:
            def factory(rid):
                return ParallelInference(net, **inf_kw)
            inf = ReplicaFleet(factory, replicas=int(replicas),
                               **(fleet_kw or {}))
        else:
            inf = ParallelInference(net, **inf_kw)
        with self._inference_lock:
            self._inference[mid] = inf
        return mid

    def attach_rag(self, net, *, vocab: int, passages, doc_vectors,
                   k: int = 4, slots: int = 4, page_size: int = 16,
                   pad_id: int = 0, knn_replicas: int = 1,
                   generate_replicas: int = 1, mid: Optional[str] = None,
                   encoder=None, index_kw: Optional[dict] = None,
                   gen_kw: Optional[dict] = None,
                   rag_kw: Optional[dict] = None) -> str:
        """Register a causal LM + document corpus for /rag, served by a
        two-tier ``RagPipeline`` (parallel/rag.py): ``doc_vectors``
        [N, D] build a knn-tier ``EmbeddingIndex`` per knn replica
        (``index_kw`` forwards — store=, partitions=, nprobe=, mesh=,
        ...), ``net`` serves per generate replica through a paged
        ``GenerationServer`` (``gen_kw`` forwards), and ``passages``
        maps retrieved doc id -> token ids for the canonical
        chunk-aligned prefix. ``net`` may be a model instance or an
        imported model id; returns the id /rag requests should name."""
        from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex
        from deeplearning4j_tpu.parallel.generation import GenerationServer
        from deeplearning4j_tpu.parallel.rag import RagPipeline

        with self._lock:
            if isinstance(net, str):
                mid = net
                net = self._net(mid)
            elif mid is None:
                mid = f"m{self._next_id}"
                self._next_id += 1
            self._models[mid] = net
            old = self._rags.pop(mid, None)
        if old is not None:
            old.close()
        vecs = np.asarray(doc_vectors, np.float32)
        ikw = dict(index_kw or {})
        gkw = dict(gen_kw or {})
        gkw.setdefault("page_size", page_size)
        gkw.setdefault("role", "generate")

        def knn_factory(rid):
            return EmbeddingIndex(vecs, **ikw)

        def gen_factory(rid):
            return GenerationServer(net, vocab, slots=slots, **gkw)

        pipe = RagPipeline(knn_factory, gen_factory, passages,
                           page_size=page_size, pad_id=pad_id, k=k,
                           encoder=encoder, knn_replicas=knn_replicas,
                           generate_replicas=generate_replicas,
                           **(rag_kw or {}))
        with self._lock:
            self._rags[mid] = pipe
        return mid

    def rag(self, mid: str, prompt_ids, max_tokens: int,
            query_vec=None, k: Optional[int] = None,
            temperature: float = 0.0, top_k: int = 0, seed: int = 0,
            deadline_s: Optional[float] = None) -> dict:
        """Submit one retrieval-augmented request and wait for its
        tokens + retrieval metadata. The pipeline enforces admission/
        deadline/breaker typing across both tiers; the handler maps it
        onto 429/503/504 exactly like /generate."""
        with self._lock:
            pipe = self._rags.get(mid)
        if pipe is None:
            raise UnknownModelError(
                f"unknown rag model '{mid}' — register it with "
                "attach_rag()")
        budget = deadline_s if deadline_s is not None \
            else self.request_deadline_s
        fut = pipe.submit(np.asarray(prompt_ids, np.int64),
                          int(max_tokens), query_vec=query_vec, k=k,
                          temperature=float(temperature),
                          top_k=int(top_k), seed=int(seed),
                          deadline_s=budget)
        try:
            out = fut.result(timeout=None if budget is None
                             else budget + 30.0)
        except Exception:
            self._m_failed.inc()
            raise
        self._m_completed.inc()
        return {"tokens": np.asarray(out).tolist(),
                "docs": [int(d) for d in fut._rag_docs],
                "prefix_len": int(fut._rag_prefix_len)}

    def generate(self, mid: str, prompt_ids, max_tokens: int,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 deadline_s: Optional[float] = None) -> list:
        """Submit one generation request and wait for its tokens. The
        GenerationServer enforces admission/deadline/breaker typing; the
        handler maps those onto 429/503/504 like /predict."""
        with self._lock:
            gen = self._generators.get(mid)
        if gen is None:
            raise UnknownModelError(
                f"unknown generation model '{mid}' — register it with "
                "attach_generation()")
        budget = deadline_s if deadline_s is not None \
            else self.request_deadline_s
        fut = gen.submit(np.asarray(prompt_ids, np.int64),
                         int(max_tokens), temperature=float(temperature),
                         top_k=int(top_k), seed=int(seed),
                         deadline_s=budget)
        try:
            # the server resolves deadlined requests itself; the extra
            # slack only guards a wedged loop thread from hanging HTTP
            out = fut.result(timeout=None if budget is None
                             else budget + 30.0)
        except Exception:
            self._m_failed.inc()
            raise
        self._m_completed.inc()
        return np.asarray(out).tolist()

    def list_models(self) -> list:
        with self._lock:
            return sorted(self._models)

    def stats(self) -> dict:
        """Per-server serving counters (the /stats endpoint body): the
        observable surface for the UI, bench, and ops. Counters come off
        the registry; the legacy key set and order are preserved
        byte-for-byte."""
        out = {"retried": int(self._m_retried.value),
               "expired": int(self._m_expired.value),
               "rejected_circuit": int(self._m_rejected_circuit.value),
               "completed": int(self._m_completed.value),
               "failed": int(self._m_failed.value)}
        out.update(accepted=self.admission.accepted,
                   rejected=self.admission.rejected,
                   pending=self.admission.pending,
                   breaker_state=self.breaker.state)
        with self._lock:
            out["models"] = len(self._models)
            gens = dict(self._generators)
            rags = dict(self._rags)
        with self._inference_lock:
            infs = dict(self._inference)
        if gens:
            # fleet-served models carry a "replicas" list here: per-replica
            # health score, breaker state, in-flight depth, restart count
            out["generation"] = {mid: g.stats() for mid, g in gens.items()}
            # crash-durable serving rollup: sum each generation target's
            # handoff block (fleet-served targets expose theirs on each
            # replica's server block instead) so ops reads one number
            handoff: dict = {}
            for st in out["generation"].values():
                blocks = [st["handoff"]] if "handoff" in st else [
                    rep["server"]["handoff"]
                    for rep in st.get("replicas", ())
                    if isinstance(rep.get("server"), dict)
                    and "handoff" in rep["server"]]
                for blk in blocks:
                    for k, v in blk.items():
                        handoff[k] = handoff.get(k, 0) + v
            if handoff:
                out["handoff"] = handoff
        if infs:
            out["inference"] = {mid: i.stats() for mid, i in infs.items()}
        if rags:
            # two-tier RAG ledgers: per-model submitted/completed/...,
            # headline prefix-dedupe counters, per-tier aggregates
            out["rag"] = {mid: r.stats() for mid, r in rags.items()}
        return out

    def register_metrics(self, labels: Optional[dict],
                         registry: MetricsRegistry) -> None:
        """Expose an additional registry on GET /metrics (a broker's, a
        training health guard's, ...) with ``labels`` injected on every
        sample it contributes."""
        with self._lock:
            self._extra_metrics.append((dict(labels or {}), registry))

    def metrics_text(self) -> str:
        """Prometheus text exposition (0.0.4) over every registry this
        server can see: its own serving counters, each attached
        generation/inference target's registry labeled ``model=<id>``
        (a fleet contributes its fleet-level aggregates), any
        ``register_metrics`` extras, and the process-global registry
        (training/health telemetry). Duplicate registry objects render
        once — first labeling wins."""
        with self._lock:
            gens = dict(self._generators)
            rags = dict(self._rags)
            extras = list(self._extra_metrics)
        with self._inference_lock:
            infs = dict(self._inference)
        sources = [({}, self.metrics)]
        seen = {id(self.metrics)}
        for mid, target in (list(gens.items()) + list(infs.items())
                            + list(rags.items())):
            # a federated target exposes one source per remote host
            # (injected host= label alongside model=) via
            # metrics_sources(); plain targets expose one registry
            ms = getattr(target, "metrics_sources", None)
            if ms is not None:
                for lbls, src in ms():
                    if id(src) not in seen:
                        seen.add(id(src))
                        sources.append(({"model": mid, **(lbls or {})},
                                        src))
                continue
            reg = getattr(target, "metrics", None)
            if reg is not None and id(reg) not in seen:
                seen.add(id(reg))
                sources.append(({"model": mid}, reg))
        for labels, reg in extras:
            if id(reg) not in seen:
                seen.add(id(reg))
                sources.append((labels, reg))
        gl = global_registry()
        if id(gl) not in seen:
            sources.append(({}, gl))
        return render_text(sources)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, status, message, err_type):
                self._json({"error": message, "type": err_type}, status)

            def do_GET(self):
                if self.path == "/models":
                    self._json({"models": server.list_models()})
                elif self.path == "/stats":
                    self._json(server.stats())
                elif self.path == "/metrics":
                    body = server.metrics_text().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._error(404, "not found", "NotFound")

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    return self._error(400, "missing or malformed "
                                       "Content-Length", "BadRequest")
                if n > server.max_body_bytes:
                    # the cap bounds MEMORY, not the wire: the body is
                    # discarded in fixed-size chunks (never buffered) so
                    # the client — still blocked in send — can finish and
                    # read the 413 instead of dying on a broken pipe
                    left = n
                    while left > 0:
                        chunk = self.rfile.read(min(left, 1 << 16))
                        if not chunk:
                            break
                        left -= len(chunk)
                    return self._error(
                        413, f"request body of {n} bytes exceeds "
                        f"max_body_bytes={server.max_body_bytes}",
                        "BodyTooLarge")
                try:
                    req = json.loads(self.rfile.read(n))
                    if not isinstance(req, dict):
                        raise ValueError("JSON body must be an object")
                except (ValueError, UnicodeDecodeError) as e:
                    return self._error(400, f"malformed JSON body: {e}",
                                       "BadRequest")
                try:
                    if self.path == "/import":
                        self._json({"model":
                                    server.import_model(req["path"])})
                    elif self.path == "/fit":
                        self._json(server.fit(req["model"], req["batches"],
                                              int(req.get("epochs", 1))))
                    elif self.path == "/evaluate":
                        self._json(server.evaluate(req["model"],
                                                   req["batches"]))
                    elif self.path == "/predict":
                        self._json({"output": server.predict(
                            req["model"], req["features"],
                            req.get("deadline_s"))})
                    elif self.path == "/generate":
                        self._json({"tokens": server.generate(
                            req["model"], req["prompt_ids"],
                            int(req["max_tokens"]),
                            float(req.get("temperature", 0.0)),
                            int(req.get("top_k", 0)),
                            int(req.get("seed", 0)),
                            req.get("deadline_s"))})
                    elif self.path == "/rag":
                        self._json(server.rag(
                            req["model"], req["prompt_ids"],
                            int(req["max_tokens"]),
                            req.get("query_vec"),
                            req.get("k"),
                            float(req.get("temperature", 0.0)),
                            int(req.get("top_k", 0)),
                            int(req.get("seed", 0)),
                            req.get("deadline_s")))
                    else:
                        self._error(404, "not found", "NotFound")
                except UnknownModelError as e:
                    self._error(404, str(e.args[0] if e.args else e),
                                type(e).__name__)
                except tuple(_STATUS) as e:
                    status = next(s for c, s in _STATUS.items()
                                  if isinstance(e, c))
                    self._error(status, str(e), type(e).__name__)
                except (KeyError, TypeError, ValueError, OSError) as e:
                    # bad request shape / unreadable batch paths
                    self._error(400, f"{type(e).__name__}: {e}",
                                "BadRequest")
                except Exception as e:  # noqa: BLE001 — structured, not a
                    # traceback-driven blank 500
                    self._error(500, f"{type(e).__name__}: {e}",
                                "InternalError")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        with self._lock:
            gens = list(self._generators.values())
            self._generators.clear()
            gens.extend(self._rags.values())
            self._rags.clear()
        with self._inference_lock:
            gens.extend(self._inference.values())
            self._inference.clear()
        for g in gens:
            g.close()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
