"""DeepWalk / Node2Vec (reference: deeplearning4j-graph
graph/models/deepwalk/DeepWalk.java + GraphHuffman.java — skip-gram with
hierarchical softmax over random walks).

Walks become token sequences and the whole nlp SequenceVectors engine (vocab,
Huffman, jitted skipgram scatter steps) does the training — the exact reuse
the reference gets from its GraphVectorsImpl/InMemoryGraphLookupTable pair.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.graph.walks import (
    Node2VecWalkIterator,
    RandomWalkIterator,
)
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors


class DeepWalk:
    """reference: DeepWalk.Builder (vectorSize, windowSize, learningRate) +
    fit(GraphWalkIterator)."""

    def __init__(self, vector_size: int = 100, window: int = 5,
                 learning_rate: float = 0.025, walk_length: int = 40,
                 walks_per_vertex: int = 10, epochs: int = 1,
                 negative: int = 0, use_hierarchic_softmax: bool = True,
                 seed: int = 12345):
        self.vector_size = vector_size
        self.window = window
        self.learning_rate = learning_rate
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.epochs = epochs
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.seed = seed
        self._sv: SequenceVectors = None

    def _walks(self, graph):
        return RandomWalkIterator(graph, self.walk_length,
                                  self.walks_per_vertex, seed=self.seed)

    def fit(self, graph) -> "DeepWalk":
        walks = [[str(v) for v in walk] for walk in self._walks(graph)]
        self._sv = SequenceVectors(
            layer_size=self.vector_size, window=self.window,
            min_word_frequency=1, epochs=self.epochs,
            learning_rate=self.learning_rate, negative=self.negative,
            use_hierarchic_softmax=self.use_hs, seed=self.seed)
        self._sv.fit(walks)
        return self

    def vertex_vector(self, v: int) -> np.ndarray:
        return self._sv.word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(str(a), str(b))

    def verts_nearest(self, v: int, top_n: int = 10) -> list:
        return [(int(w), s)
                for w, s in self._sv.words_nearest(str(v), top_n)]


class Node2Vec(DeepWalk):
    """p/q-biased DeepWalk (reference: models/node2vec/Node2Vec.java)."""

    def __init__(self, p: float = 1.0, q: float = 1.0, **kw):
        super().__init__(**kw)
        self.p = p
        self.q = q

    def _walks(self, graph):
        return Node2VecWalkIterator(graph, self.walk_length,
                                    self.walks_per_vertex, p=self.p,
                                    q=self.q, seed=self.seed)
