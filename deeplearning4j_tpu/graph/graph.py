"""In-memory graph (reference: deeplearning4j-graph
graph/api/IGraph.java + graph/graph/Graph.java — adjacency-list graph with
optional edge weights)."""

from __future__ import annotations


class Graph:
    def __init__(self, num_vertices: int, directed: bool = False):
        self.num_vertices_ = num_vertices
        self.directed = directed
        self._adj: list = [[] for _ in range(num_vertices)]  # (to, weight)

    @staticmethod
    def from_edges(num_vertices: int, edges, directed: bool = False
                   ) -> "Graph":
        g = Graph(num_vertices, directed)
        for e in edges:
            if len(e) == 2:
                g.add_edge(e[0], e[1])
            else:
                g.add_edge(e[0], e[1], e[2])
        return g

    def add_edge(self, a: int, b: int, weight: float = 1.0) -> None:
        self._adj[a].append((b, weight))
        if not self.directed:
            self._adj[b].append((a, weight))

    def num_vertices(self) -> int:
        return self.num_vertices_

    def neighbors(self, v: int) -> list:
        return [t for t, _ in self._adj[v]]

    def edges_out(self, v: int) -> list:
        return list(self._adj[v])

    def degree(self, v: int) -> int:
        return len(self._adj[v])
