"""Random-walk generators (reference: deeplearning4j-graph
graph/iterator/RandomWalkIterator.java, WeightedRandomWalkGraphIteratorProvider,
and node2vec's p/q-biased second-order walks)."""

from __future__ import annotations

import numpy as np


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex (reference:
    RandomWalkIterator.java; NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED)."""

    def __init__(self, graph, walk_length: int, walks_per_vertex: int = 1,
                 seed: int = 0):
        self.graph = graph
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.seed = seed

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                cur = start
                for _ in range(self.walk_length - 1):
                    nbrs = self.graph.neighbors(cur)
                    cur = int(rng.choice(nbrs)) if nbrs else cur
                    walk.append(cur)
                yield walk

    def reset(self):
        pass


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transitions (reference: weighted walk
    provider)."""

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                cur = start
                for _ in range(self.walk_length - 1):
                    edges = self.graph.edges_out(cur)
                    if edges:
                        w = np.asarray([e[1] for e in edges], np.float64)
                        cur = edges[rng.choice(len(edges),
                                               p=w / w.sum())][0]
                    walk.append(cur)
                yield walk


class Node2VecWalkIterator(RandomWalkIterator):
    """Second-order p/q-biased walks (node2vec, Grover & Leskovec 2016 —
    return parameter p, in-out parameter q)."""

    def __init__(self, graph, walk_length: int, walks_per_vertex: int = 1,
                 p: float = 1.0, q: float = 1.0, seed: int = 0):
        super().__init__(graph, walk_length, walks_per_vertex, seed)
        self.p = p
        self.q = q

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                prev = None
                cur = start
                for _ in range(self.walk_length - 1):
                    nbrs = self.graph.neighbors(cur)
                    if not nbrs:
                        walk.append(cur)
                        continue
                    if prev is None:
                        nxt = int(rng.choice(nbrs))
                    else:
                        prev_nbrs = set(self.graph.neighbors(prev))
                        w = np.asarray(
                            [1.0 / self.p if n == prev
                             else (1.0 if n in prev_nbrs else 1.0 / self.q)
                             for n in nbrs], np.float64)
                        nxt = nbrs[rng.choice(len(nbrs), p=w / w.sum())]
                    walk.append(nxt)
                    prev, cur = cur, nxt
                yield walk
