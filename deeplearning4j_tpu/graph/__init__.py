"""Graph embeddings (reference: deeplearning4j-graph — IGraph API,
random-walk iterators, DeepWalk + GraphHuffman)."""

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import (
    Node2VecWalkIterator,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, Node2Vec

__all__ = ["Graph", "RandomWalkIterator", "WeightedRandomWalkIterator",
           "Node2VecWalkIterator", "DeepWalk", "Node2Vec"]
