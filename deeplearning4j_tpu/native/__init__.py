"""Native runtime components (C, loaded via ctypes).

The reference's runtime is JVM+native: ND4J C++ kernels for compute,
JavaCPP-wrapped native IO underneath DataVec ingestion. In the TPU build
the compute path's native layer IS XLA's C++ runtime (PJRT); this package
holds the framework's OWN native pieces — currently the data-loader hot
path (numeric CSV parsing, deeplearning4j_tpu/native/fastio.c).

Build contract: the shared object is compiled ON FIRST USE with the
toolchain baked into the image (cc -O2 -shared -fPIC), cached next to the
source, and every consumer falls back to the pure-Python path when the
toolchain or the build is unavailable — native is an accelerator, never a
hard dependency.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_fastio.so")
_SRC = os.path.join(_DIR, "fastio.c")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            stale = (not os.path.exists(_SO)
                     or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        except OSError:
            # source missing but a built artifact exists: use it as-is
            stale = not os.path.exists(_SO)
        if stale:
            cc = (os.environ.get("CC") or shutil.which("cc")
                  or shutil.which("gcc"))
            if cc is None:
                return None
            try:
                subprocess.run([cc, "-O2", "-shared", "-fPIC", "-o", _SO,
                                _SRC], check=True, capture_output=True,
                               timeout=120)
            except (subprocess.SubprocessError, OSError):
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.parse_numeric_csv.restype = ctypes.c_long
        lib.parse_numeric_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def parse_numeric_csv(path: str, delimiter: str = ",",
                      skip_lines: int = 0):
    """Parse a purely numeric CSV file natively -> float64 [rows, cols],
    or None when the fast path does not apply (no native lib, non-numeric
    fields, ragged rows) — callers then use the Python reader."""
    lib = _load()
    if lib is None or len(delimiter) != 1:
        return None
    try:
        with open(path, "rb") as f:
            buf = f.read() + b"\0"  # strtod needs NUL-terminated memory
    except OSError:
        return None
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    n = len(buf) - 1
    # pass 1: validate + count
    rc = lib.parse_numeric_csv(buf, n, delimiter.encode()[0], skip_lines,
                               None, ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        return None
    out = np.empty(rows.value * cols.value, np.float64)
    rc = lib.parse_numeric_csv(
        buf, n, delimiter.encode()[0], skip_lines,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        return None
    return out.reshape(rows.value, cols.value)
