"""Native runtime components (C, loaded via ctypes).

The reference's runtime is JVM+native: ND4J C++ kernels for compute,
JavaCPP-wrapped native IO underneath DataVec ingestion. In the TPU build
the compute path's native layer IS XLA's C++ runtime (PJRT); this package
holds the framework's OWN native pieces — currently the data-loader hot
path (numeric CSV parsing, deeplearning4j_tpu/native/fastio.c).

Build contract: the shared object is compiled ON FIRST USE with the
toolchain baked into the image (cc -O2 -shared -fPIC) into a gitignored
cache directory KEYED BY SOURCE HASH — no prebuilt binary is ever
committed or loaded, so the bytes that run provably come from the .c file
under review (a hash mismatch simply builds a new artifact). Every
consumer falls back to the pure-Python path when the toolchain or the
build is unavailable — native is an accelerator, never a hard dependency.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_CACHE_DIR = os.path.join(_DIR, ".cache")
_SRC = os.path.join(_DIR, "fastio.c")

_lock = threading.Lock()
_lib = None
_tried = False


def _build(src: str, stem: str, flags, libs=()) -> "str | None":
    """Compile ``src`` into the gitignored cache dir, the artifact named
    by the source's content hash: a reviewed-source edit can never load a
    stale binary, and the cache survives across processes. Returns the
    .so path, or None when the toolchain/build is unavailable."""
    try:
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    so = os.path.join(_CACHE_DIR, f"{stem}-{digest}.so")
    if os.path.exists(so):
        return so
    cc = (os.environ.get("CC") or shutil.which("cc")
          or shutil.which("gcc"))
    if cc is None:
        return None
    tmp = f"{so}.tmp{os.getpid()}"
    try:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        subprocess.run([cc, *flags, "-o", tmp, src, *libs], check=True,
                       capture_output=True, timeout=120)
        os.replace(tmp, so)  # atomic: concurrent builders race benignly
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return so


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build(_SRC, "_fastio", ["-O2", "-shared", "-fPIC"])
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        lib.parse_numeric_csv.restype = ctypes.c_long
        lib.parse_numeric_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------- skipgram
_SG_SRC = os.path.join(_DIR, "skipgram.c")
_sg_lib = None
_sg_tried = False


def _load_skipgram():
    global _sg_lib, _sg_tried
    with _lock:
        if _sg_tried:
            return _sg_lib
        _sg_tried = True
        # -O3 -ffast-math: the dot/axpy inner loops vectorize; the
        # reference's libnd4j kernel is likewise SIMD C++
        so = _build(_SG_SRC, "_skipgram",
                    ["-O3", "-ffast-math", "-shared", "-fPIC"],
                    libs=["-lm"])
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        lib.skipgram_train.restype = ctypes.c_long
        lib.skipgram_train.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_int), ctypes.c_long,
            ctypes.POINTER(ctypes.c_int), ctypes.c_long,
            ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_ulonglong]
        _sg_lib = lib
        return _sg_lib


def skipgram_native_available() -> bool:
    return _load_skipgram() is not None


def skipgram_train(syn0, syn1neg, corpus, table, *, window: int,
                   negative: int, alpha: float, min_alpha: float,
                   epochs: int = 1, seed: int = 1):
    """In-place native skip-gram NS training (the AggregateSkipGram hot
    loop, SkipGram.java:215-272 / its libnd4j kernel). ``syn0``/``syn1neg``
    are float32 C-contiguous [vocab, layer]; ``corpus`` int32 word indices
    with -1 sentence separators; ``table`` int32 unigram^0.75 sampling
    table. Returns trained pair count, or None when native is
    unavailable (callers use the device path)."""
    lib = _load_skipgram()
    if lib is None:
        return None
    syn0 = np.ascontiguousarray(syn0, np.float32)
    syn1neg = np.ascontiguousarray(syn1neg, np.float32)
    corpus = np.ascontiguousarray(corpus, np.int32)
    table = np.ascontiguousarray(table, np.int32)
    fp = ctypes.POINTER(ctypes.c_float)
    ip = ctypes.POINTER(ctypes.c_int)
    pairs = lib.skipgram_train(
        syn0.ctypes.data_as(fp), syn1neg.ctypes.data_as(fp),
        syn0.shape[0], syn0.shape[1],
        corpus.ctypes.data_as(ip), len(corpus),
        table.ctypes.data_as(ip), len(table),
        window, negative, alpha, min_alpha, epochs, seed)
    if pairs < 0:
        return None
    return pairs, syn0, syn1neg


def parse_numeric_csv(path: str, delimiter: str = ",",
                      skip_lines: int = 0):
    """Parse a purely numeric CSV file natively -> float64 [rows, cols],
    or None when the fast path does not apply (no native lib, non-numeric
    fields, ragged rows) — callers then use the Python reader."""
    lib = _load()
    if lib is None or len(delimiter) != 1:
        return None
    try:
        with open(path, "rb") as f:
            buf = f.read() + b"\0"  # strtod needs NUL-terminated memory
    except OSError:
        return None
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    n = len(buf) - 1
    # pass 1: validate + count
    rc = lib.parse_numeric_csv(buf, n, delimiter.encode()[0], skip_lines,
                               None, ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        return None
    out = np.empty(rows.value * cols.value, np.float64)
    rc = lib.parse_numeric_csv(
        buf, n, delimiter.encode()[0], skip_lines,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        return None
    return out.reshape(rows.value, cols.value)


NATIVE_MAX_LAYER = 4096  # fixed accumulator size in skipgram.c


def _bind_pairs(lib):
    """Bind pairs_train, or None when the loaded .so predates it (stale
    artifact with equal mtime): native stays a soft dependency."""
    if not hasattr(lib, "pairs_train"):
        return None
    if not hasattr(lib, "_pairs_bound"):
        lib.pairs_train.restype = ctypes.c_long
        lib.pairs_train.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int), ctypes.c_long,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_ulonglong]
        lib._pairs_bound = True
    return lib


def ns_pairs_train(syn0, syn1neg, rows, targets, table, *, negative: int,
                   alpha: float, min_alpha: float, epochs: int = 1,
                   seed: int = 1):
    """In-place native negative-sampling pair training: rows[i] (syn0
    input row) predicts targets[i] (syn1neg output row) — the DBOW hot
    loop (sequence/DBOW.java) and any other pre-generated pair stream.
    Returns trained pair count + updated arrays, or None when the native
    library is unavailable."""
    lib = _load_skipgram()
    if lib is None or _bind_pairs(lib) is None:
        return None
    syn0 = np.ascontiguousarray(syn0, np.float32)
    syn1neg = np.ascontiguousarray(syn1neg, np.float32)
    rows = np.ascontiguousarray(rows, np.int32)
    targets = np.ascontiguousarray(targets, np.int32)
    table = np.ascontiguousarray(table, np.int32)
    fp = ctypes.POINTER(ctypes.c_float)
    ip = ctypes.POINTER(ctypes.c_int)
    n = lib.pairs_train(
        syn0.ctypes.data_as(fp), syn1neg.ctypes.data_as(fp),
        syn0.shape[1],
        rows.ctypes.data_as(ip), targets.ctypes.data_as(ip), len(rows),
        table.ctypes.data_as(ip), len(table),
        negative, alpha, min_alpha, epochs, seed)
    if n < 0:
        return None
    return n, syn0, syn1neg


def _bind_cbow(lib):
    """Bind cbow_train, or None when the loaded .so predates it."""
    if not hasattr(lib, "cbow_train"):
        return None
    if not hasattr(lib, "_cbow_bound"):
        lib.cbow_train.restype = ctypes.c_long
        lib.cbow_train.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_int), ctypes.c_long,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_long,
            ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_ulonglong]
        lib._cbow_bound = True
    return lib


NATIVE_MAX_WINDOW = 64  # fixed context buffer in cbow_train


def cbow_native_available() -> bool:
    """The loaded .so exports cbow_train (a stale artifact may not)."""
    lib = _load_skipgram()
    return lib is not None and _bind_cbow(lib) is not None


def pairs_native_available() -> bool:
    """The loaded .so exports pairs_train (a stale artifact may not)."""
    lib = _load_skipgram()
    return lib is not None and _bind_pairs(lib) is not None


def cbow_train(syn0, syn1neg, corpus, table, *, window: int, negative: int,
               alpha: float, min_alpha: float, epochs: int = 1,
               seed: int = 1, labels=None):
    """In-place native CBOW/DM training (CBOW.java / DM.java hot loop):
    the averaged context window — plus the per-position ``labels`` row
    for DM — predicts the center word via negative sampling. Returns
    trained position count + updated arrays, or None when native is
    unavailable."""
    lib = _load_skipgram()
    if lib is None or _bind_cbow(lib) is None:
        return None
    syn0 = np.ascontiguousarray(syn0, np.float32)
    syn1neg = np.ascontiguousarray(syn1neg, np.float32)
    corpus = np.ascontiguousarray(corpus, np.int32)
    table = np.ascontiguousarray(table, np.int32)
    fp = ctypes.POINTER(ctypes.c_float)
    ip = ctypes.POINTER(ctypes.c_int)
    if labels is not None:
        labels = np.ascontiguousarray(labels, np.int32)
        lab_ptr = labels.ctypes.data_as(ip)
    else:
        lab_ptr = None
    n = lib.cbow_train(
        syn0.ctypes.data_as(fp), syn1neg.ctypes.data_as(fp),
        syn0.shape[1],
        corpus.ctypes.data_as(ip), len(corpus), lab_ptr,
        table.ctypes.data_as(ip), len(table),
        window, negative, alpha, min_alpha, epochs, seed)
    if n < 0:
        return None
    return n, syn0, syn1neg
