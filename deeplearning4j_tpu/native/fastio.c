/* Native data-loader hot path: numeric CSV -> double array.
 *
 * Reference parity note: the reference's ingestion layer (DataVec) runs on
 * the JVM with native-backed parsing underneath; this is the TPU build's
 * equivalent native component for the same role (see
 * deeplearning4j_tpu/native/__init__.py for the build/fallback contract).
 *
 * Two-pass API so the caller allocates exactly once:
 *   pass 1 (out == NULL): validate + count values/rows/cols
 *   pass 2 (out != NULL): fill
 * Returns 0 on success, -1 on anything the fast path cannot represent
 * exactly like the Python csv+float() path would — non-numeric field,
 * ragged rows, empty field, whitespace-only line, any numeric spelling
 * Python float() rejects (hex floats, locale decimal commas). The caller
 * then falls back to the general-purpose Python reader: output must NEVER
 * depend on whether the native library is available.
 */

#include <stdlib.h>

/* characters that may appear in a float() -accepted decimal literal */
static int num_char(char ch) {
    return (ch >= '0' && ch <= '9') || ch == '+' || ch == '-' ||
           ch == '.' || ch == 'e' || ch == 'E';
}

static int soft_space(char ch, char delim) {
    return (ch == ' ' || ch == '\t' || ch == '\r') && ch != delim;
}

long parse_numeric_csv(const char *buf, long len, char delim, long skip,
                       double *out, long *rows, long *cols) {
    const char *p = buf, *end = buf + len;
    long r = 0, c0 = -1, n = 0;
    while (skip > 0 && p < end) {
        while (p < end && *p != '\n') p++;
        if (p < end) p++;
        skip--;
    }
    while (p < end) {
        /* classify the line: truly empty (only \r) is skipped — the
         * Python csv reader yields [] for it and the reader drops empty
         * rows; a line of spaces/tabs is a ONE-FIELD STRING record on the
         * Python path, so the fast path must decline, not skip */
        const char *q = p;
        int empty = 1, spacey = 1;
        while (q < end && *q != '\n') {
            if (*q != '\r') {
                empty = 0;
                if (*q != ' ' && *q != '\t') spacey = 0;
            }
            q++;
        }
        if (empty) { p = q < end ? q + 1 : end; continue; }
        if (spacey) return -1;
        long c = 0;
        for (;;) {
            while (p < end && soft_space(*p, delim)) p++;
            /* empty field: at delimiter, end of line, or end of buffer.
             * Checked BEFORE strtod because strtod itself skips newlines
             * and (for delim=' ') delimiter spaces as plain whitespace. */
            if (p >= end || *p == '\n' || *p == delim) return -1;
            char *fend;
            double v = strtod(p, &fend);
            if (fend == p) return -1;            /* non-numeric field */
            /* reject spellings Python float() would not accept the same
             * way (0x10, locale '3,14', ...): every consumed character
             * must come from the plain decimal alphabet */
            for (const char *t = p; t < fend; t++)
                if (!num_char(*t)) return -1;
            while (fend < (char *)end && soft_space(*fend, delim)) fend++;
            if (out) out[n] = v;
            n++; c++;
            if (fend >= (char *)end || *fend == '\n') {
                p = fend < (char *)end ? fend + 1 : end;
                break;
            }
            if (*fend != delim) return -1;
            p = fend + 1;
            if (p >= end) return -1;             /* trailing delimiter */
        }
        if (c0 < 0) c0 = c;
        else if (c != c0) return -1;             /* ragged rows */
        r++;
    }
    if (r == 0) return -1;
    *rows = r;
    *cols = c0;
    return 0;
}
