/* Skip-gram negative-sampling training hot loop.
 *
 * Faithful stand-in for the reference's native hot op: DL4J's
 * SkipGram.java:215-272 dispatches an AggregateSkipGram whose
 * implementation is a libnd4j C++ kernel doing exactly this per
 * (center, context) pair: dot(syn0[w], syn1neg[c]) -> sigmoid ->
 * gradient axpy on both tables, negatives drawn from the unigram^0.75
 * table, linear learning-rate decay.  Used two ways:
 *   1. as the measured LOCAL BASELINE of what the reference's native
 *      path achieves on this host's CPU (profiles/w2v_baseline.py);
 *   2. as an optional native trainer behind Word2Vec (the same
 *      helper-SPI pattern as the cuDNN helpers / native CSV loader:
 *      an accelerator, never a hard dependency).
 *
 * Single-threaded: this image exposes one CPU core (nproc=1), so the
 * reference's HogWild thread fan-out has no parallelism to exploit
 * here; the kernel is the per-thread inner loop either way.
 */

#include <math.h>
#include <stddef.h>

#define MAX_EXP 6.0f
#define EXP_TABLE_SIZE 1024

static float exp_table[EXP_TABLE_SIZE];
static int exp_table_ready = 0;

static void build_exp_table(void) {
    for (int i = 0; i < EXP_TABLE_SIZE; i++) {
        float x = ((float)i / EXP_TABLE_SIZE * 2.0f - 1.0f) * MAX_EXP;
        float e = expf(x);
        exp_table[i] = e / (e + 1.0f);  /* sigmoid */
    }
    exp_table_ready = 1;
}

static inline float fast_sigmoid(float x) {
    if (x >= MAX_EXP) return 1.0f;
    if (x <= -MAX_EXP) return 0.0f;
    int idx = (int)((x + MAX_EXP) * (EXP_TABLE_SIZE / (2.0f * MAX_EXP)));
    if (idx < 0) idx = 0;
    if (idx >= EXP_TABLE_SIZE) idx = EXP_TABLE_SIZE - 1;
    return exp_table[idx];
}

static inline unsigned long long next_rand(unsigned long long *s) {
    *s = *s * 25214903917ULL + 11ULL; /* the classic word2vec LCG */
    return *s;
}

/* Train over a flat corpus of word indices with sentence boundaries
 * marked by -1.  Returns the number of (center, context) pairs trained.
 *
 * syn0, syn1neg: [vocab, layer] row-major float32, updated in place.
 * table: unigram^0.75 negative-sampling table of word indices.
 * alpha decays linearly to min_alpha over total_words * epochs. */
long skipgram_train(float *syn0, float *syn1neg, long vocab, long layer,
                    const int *corpus, long corpus_len,
                    const int *table, long table_len,
                    int window, int negative,
                    float alpha, float min_alpha, int epochs,
                    unsigned long long seed) {
    (void)vocab;
    if (!exp_table_ready) build_exp_table();
    if (window < 1) return -1; /* %0 in the reduced-window draw = SIGFPE */
    long pairs = 0;
    long total = (long)corpus_len * epochs;
    long seen = 0;
    unsigned long long rng = seed ? seed : 1ULL;
    float neu1e[4096]; /* layer <= 4096 */
    if (layer > 4096) return -1;

    for (int ep = 0; ep < epochs; ep++) {
        long sent_start = 0;
        for (long pos = 0; pos < corpus_len; pos++) {
            int w = corpus[pos];
            if (w < 0) { sent_start = pos + 1; continue; }
            seen++;
            float lr = alpha * (1.0f - (float)seen / (float)(total + 1));
            if (lr < min_alpha) lr = min_alpha;
            /* reduced window, word2vec convention */
            int b = (int)(next_rand(&rng) % (unsigned)window);
            for (long cpos = pos - window + b; cpos <= pos + window - b;
                 cpos++) {
                if (cpos == pos || cpos < sent_start || cpos >= corpus_len)
                    continue;
                int c = corpus[cpos];
                if (c < 0) break; /* sentence boundary */
                /* train pair (center=w predicts context=c):
                 * rows: syn0[c] is the input vector in the reference's
                 * convention (context predicts center across the window
                 * loop — symmetric over the corpus either way) */
                const long lw = (long)w * layer;
                float *in = syn0 + (long)c * layer;
                for (long k = 0; k < layer; k++) neu1e[k] = 0.0f;
                for (int d = 0; d < negative + 1; d++) {
                    long target;
                    float label;
                    if (d == 0) {
                        target = w;
                        label = 1.0f;
                    } else {
                        target = table[(next_rand(&rng) >> 16) % table_len];
                        if (target == w) continue;
                        label = 0.0f;
                    }
                    float *out = syn1neg + target * layer;
                    float dot = 0.0f;
                    for (long k = 0; k < layer; k++) dot += in[k] * out[k];
                    float g = (label - fast_sigmoid(dot)) * lr;
                    for (long k = 0; k < layer; k++) {
                        neu1e[k] += g * out[k];
                        out[k] += g * in[k];
                    }
                }
                for (long k = 0; k < layer; k++) in[k] += neu1e[k];
                pairs++;
                (void)lw;
            }
        }
    }
    return pairs;
}

/* Generic negative-sampling pair trainer: rows[i] (input vector in syn0)
 * predicts targets[i] (output row in syn1neg), negatives from the
 * unigram table.  The DBOW hot loop (reference: sequence/DBOW.java — a
 * document's label row predicts every document word) is exactly this
 * with rows = label per position; also reusable for any pre-generated
 * pair stream.  Same LR decay / sigmoid table / LCG as skipgram_train. */
long pairs_train(float *syn0, float *syn1neg, long layer,
                 const int *rows, const int *targets, long n_pairs,
                 const int *table, long table_len,
                 int negative, float alpha, float min_alpha, int epochs,
                 unsigned long long seed) {
    if (!exp_table_ready) build_exp_table();
    if (layer > 4096) return -1;
    long done = 0;
    long total = n_pairs * (long)epochs;
    unsigned long long rng = seed ? seed : 1ULL;
    float neu1e[4096];

    for (int ep = 0; ep < epochs; ep++) {
        for (long i = 0; i < n_pairs; i++) {
            int r = rows[i];
            int w = targets[i];
            if (r < 0 || w < 0) continue;
            done++;
            float lr = alpha * (1.0f - (float)done / (float)(total + 1));
            if (lr < min_alpha) lr = min_alpha;
            float *in = syn0 + (long)r * layer;
            for (long k = 0; k < layer; k++) neu1e[k] = 0.0f;
            for (int d = 0; d < negative + 1; d++) {
                long target;
                float label;
                if (d == 0) {
                    target = w;
                    label = 1.0f;
                } else {
                    target = table[(next_rand(&rng) >> 16) % table_len];
                    if (target == w) continue;
                    label = 0.0f;
                }
                float *out = syn1neg + target * layer;
                float dot = 0.0f;
                for (long k = 0; k < layer; k++) dot += in[k] * out[k];
                float g = (label - fast_sigmoid(dot)) * lr;
                for (long k = 0; k < layer; k++) {
                    neu1e[k] += g * out[k];
                    out[k] += g * in[k];
                }
            }
            for (long k = 0; k < layer; k++) in[k] += neu1e[k];
        }
    }
    return done;
}

/* CBOW / DM hot loop (reference: impl/elements/CBOW.java and
 * sequence/DM.java — DM is CBOW with the document's label row prepended
 * to every context window):  the averaged context (plus optional label
 * row) predicts the center word through negative sampling; the gradient
 * is distributed back to every contributing row.  labels may be NULL
 * (plain CBOW) or hold one syn0 row id per corpus position (-1 = none).
 * Same LR decay / sigmoid table / LCG as skipgram_train. */
long cbow_train(float *syn0, float *syn1neg, long layer,
                const int *corpus, long corpus_len,
                const int *labels,
                const int *table, long table_len,
                int window, int negative,
                float alpha, float min_alpha, int epochs,
                unsigned long long seed) {
    if (!exp_table_ready) build_exp_table();
    if (layer > 4096) return -1;
    if (window < 1) return -1; /* %0 in the reduced-window draw = SIGFPE */
    long trained = 0;
    long total = (long)corpus_len * epochs;
    long seen = 0;
    unsigned long long rng = seed ? seed : 1ULL;
    float neu1[4096], neu1e[4096];
    long ctx[2 * 64 + 1]; /* window <= 64 plus the optional label row */
    if (window > 64) return -1;

    for (int ep = 0; ep < epochs; ep++) {
        long sent_start = 0;
        for (long pos = 0; pos < corpus_len; pos++) {
            int w = corpus[pos];
            if (w < 0) { sent_start = pos + 1; continue; }
            seen++;
            float lr = alpha * (1.0f - (float)seen / (float)(total + 1));
            if (lr < min_alpha) lr = min_alpha;
            int b = (int)(next_rand(&rng) % (unsigned)window);
            long n_ctx = 0;
            for (long cpos = pos - window + b; cpos <= pos + window - b;
                 cpos++) {
                if (cpos == pos || cpos < sent_start || cpos >= corpus_len)
                    continue;
                int c = corpus[cpos];
                if (c < 0) break;
                ctx[n_ctx++] = c;
            }
            if (labels && labels[pos] >= 0)
                ctx[n_ctx++] = labels[pos];
            if (n_ctx == 0) continue;
            float inv = 1.0f / (float)n_ctx;
            for (long k = 0; k < layer; k++) {
                float acc = 0.0f;
                for (long j = 0; j < n_ctx; j++)
                    acc += syn0[ctx[j] * layer + k];
                neu1[k] = acc * inv;
                neu1e[k] = 0.0f;
            }
            for (int d = 0; d < negative + 1; d++) {
                long target;
                float label;
                if (d == 0) {
                    target = w;
                    label = 1.0f;
                } else {
                    target = table[(next_rand(&rng) >> 16) % table_len];
                    if (target == w) continue;
                    label = 0.0f;
                }
                float *out = syn1neg + target * layer;
                float dot = 0.0f;
                for (long k = 0; k < layer; k++) dot += neu1[k] * out[k];
                float g = (label - fast_sigmoid(dot)) * lr;
                for (long k = 0; k < layer; k++) {
                    neu1e[k] += g * out[k];
                    out[k] += g * neu1[k];
                }
            }
            for (long j = 0; j < n_ctx; j++) {
                float *in = syn0 + ctx[j] * layer;
                for (long k = 0; k < layer; k++) in[k] += neu1e[k];
            }
            trained++;
        }
    }
    return trained;
}
