"""Tensor op facade: the measured ND4J op surface re-expressed over jax.numpy/lax.

Covers the op families DL4J actually calls into ND4J for (SURVEY.md section 2.2):
gemm, conv (im2col-free via lax.conv_general_dilated), pooling (lax.reduce_window),
elementwise transforms, RNG, argmax/gather, and activation/loss function objects.
"""

from deeplearning4j_tpu.ops.activations import Activation, get_activation
from deeplearning4j_tpu.ops.losses import LossFunction, get_loss
