"""Activation functions (parity with ND4J IActivation set used by DL4J layer configs).

Reference surface: DL4J's ``Activation`` enum (nd4j IActivation impls) referenced from
layer builders, e.g. ``nn/conf/layers/Layer.java`` activation field. Each activation
here is a pure jax function; gradients come from jax autodiff rather than the
hand-written ``backprop(in, epsilon)`` of the reference.

All functions operate elementwise on arrays of any shape except ``softmax`` which
normalises over the last axis (the feature axis in our NHWC / [batch, time, feature]
layouts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_REGISTRY: dict[str, "Activation"] = {}


class Activation:
    """A named activation function. Callable; serialises to its name."""

    def __init__(self, name: str, fn):
        self.name = name
        self._fn = fn

    def __call__(self, x):
        return self._fn(x)

    def __repr__(self):  # pragma: no cover
        return f"Activation({self.name})"

    def __eq__(self, other):
        return isinstance(other, Activation) and other.name == self.name

    def __hash__(self):
        return hash(self.name)


def _register(name: str, fn) -> Activation:
    act = Activation(name, fn)
    _REGISTRY[name] = act
    return act


def get_activation(name) -> Activation:
    """Resolve an activation by name (case-insensitive) or pass through an Activation."""
    if isinstance(name, Activation):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _rationaltanh(x):
    # tanh approximation: 1.7159 * tanh(2x/3) as used by the reference's RationalTanh
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


IDENTITY = _register("identity", lambda x: x)
LINEAR = _REGISTRY["identity"]
_register("linear", lambda x: x)
RELU = _register("relu", jax.nn.relu)
RELU6 = _register("relu6", lambda x: jnp.clip(x, 0.0, 6.0))
LEAKYRELU = _register("leakyrelu", lambda x: jax.nn.leaky_relu(x, 0.01))
TANH = _register("tanh", jnp.tanh)
SIGMOID = _register("sigmoid", jax.nn.sigmoid)
SOFTMAX = _register("softmax", lambda x: jax.nn.softmax(x, axis=-1))
SOFTPLUS = _register("softplus", jax.nn.softplus)
SOFTSIGN = _register("softsign", jax.nn.soft_sign)
ELU = _register("elu", jax.nn.elu)
SELU = _register("selu", jax.nn.selu)
GELU = _register("gelu", jax.nn.gelu)
SILU = _register("silu", jax.nn.silu)
SWISH = _register("swish", jax.nn.silu)
CUBE = _register("cube", lambda x: x ** 3)
HARDTANH = _register("hardtanh", _hardtanh)
HARDSIGMOID = _register("hardsigmoid", _hardsigmoid)
RATIONALTANH = _register("rationaltanh", _rationaltanh)
RECTIFIEDTANH = _register("rectifiedtanh", _rectifiedtanh)
