"""Loss functions (parity with ND4J ILossFunction set used by DL4J output layers).

Reference surface: the ``LossFunctions.LossFunction`` enum consumed by
``nn/conf/layers/OutputLayer``/``RnnOutputLayer``/``LossLayer`` builders. Semantics
follow the reference: per-example loss is the SUM over output units; the reported
score is the MEAN over (unmasked) examples. Masks (per-example or per-timestep) zero
out contributions and are excluded from the mean denominator.

Each loss takes ``(labels, preactivations, activation, mask)`` and exposes:
- ``score(...)``     -> scalar mean loss
- ``score_per_example(...)`` -> [batch] (or [batch*time]) vector

Losses are computed from *pre-activations* plus the output activation function so
that numerically-fused forms (softmax+xent, sigmoid+bce) can be used, mirroring how
the reference fuses ``LossMCXENT`` with softmax output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.activations import Activation, get_activation

_EPS = 1e-7

_REGISTRY: dict[str, "LossFunction"] = {}


class LossFunction:
    """A named loss. ``per_example(labels, preact, activation)`` -> [batch] losses."""

    def __init__(self, name: str, fn, *, probs_fn=None):
        self.name = name
        # fn(labels, preact, activation_obj) -> per-example loss, reduced over features
        self._fn = fn

    def per_example(self, labels, preact, activation: Activation, weights=None):
        return self._fn(labels, preact, activation, weights)

    def score(self, labels, preact, activation: Activation, mask=None, weights=None):
        """Mean-over-examples loss, matching DL4J's computeScore(average=true)."""
        per_ex = self.per_example(labels, preact, activation, weights)
        if mask is not None:
            mask = mask.reshape(per_ex.shape).astype(per_ex.dtype)
            total = jnp.sum(per_ex * mask)
            denom = jnp.maximum(jnp.sum(mask), 1.0)
            return total / denom
        return jnp.mean(per_ex)

    def __repr__(self):  # pragma: no cover
        return f"LossFunction({self.name})"

    def __eq__(self, other):
        return isinstance(other, LossFunction) and other.name == self.name

    def __hash__(self):
        return hash(self.name)


def _register(name: str, fn) -> LossFunction:
    loss = LossFunction(name, fn)
    _REGISTRY[name] = loss
    return loss


def get_loss(name) -> LossFunction:
    if isinstance(name, LossFunction):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def _apply_weights(per_feature, weights):
    if weights is not None:
        per_feature = per_feature * weights
    return per_feature


def _mcxent(labels, preact, activation, weights):
    """Multi-class cross entropy. Fused log-softmax path when output act is softmax."""
    if activation.name == "softmax":
        logp = jax.nn.log_softmax(preact, axis=-1)
    else:
        p = jnp.clip(activation(preact), _EPS, 1.0 - _EPS)
        logp = jnp.log(p)
    return -jnp.sum(_apply_weights(labels * logp, weights), axis=-1)


def _xent(labels, preact, activation, weights):
    """Binary cross entropy (per-unit), fused with sigmoid when applicable."""
    if activation.name == "sigmoid":
        # stable: max(x,0) - x*z + log(1+exp(-|x|))
        x = preact
        per = jnp.maximum(x, 0.0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x)))
    else:
        p = jnp.clip(activation(preact), _EPS, 1.0 - _EPS)
        per = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    return jnp.sum(_apply_weights(per, weights), axis=-1)


def _mse(labels, preact, activation, weights):
    out = activation(preact)
    return jnp.sum(_apply_weights((labels - out) ** 2, weights), axis=-1) / labels.shape[-1]


def _sse(labels, preact, activation, weights):
    out = activation(preact)
    return jnp.sum(_apply_weights((labels - out) ** 2, weights), axis=-1)


def _mae(labels, preact, activation, weights):
    out = activation(preact)
    return jnp.sum(_apply_weights(jnp.abs(labels - out), weights), axis=-1) / labels.shape[-1]


def _l1(labels, preact, activation, weights):
    out = activation(preact)
    return jnp.sum(_apply_weights(jnp.abs(labels - out), weights), axis=-1)


def _mape(labels, preact, activation, weights):
    out = activation(preact)
    per = jnp.abs((labels - out) / jnp.where(jnp.abs(labels) < _EPS, _EPS, labels)) * 100.0
    return jnp.sum(_apply_weights(per, weights), axis=-1) / labels.shape[-1]


def _msle(labels, preact, activation, weights):
    out = activation(preact)
    per = (jnp.log1p(jnp.maximum(out, -1.0 + _EPS)) - jnp.log1p(jnp.maximum(labels, -1.0 + _EPS))) ** 2
    return jnp.sum(_apply_weights(per, weights), axis=-1) / labels.shape[-1]


def _kld(labels, preact, activation, weights):
    out = jnp.clip(activation(preact), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    per = lab * (jnp.log(lab) - jnp.log(out))
    return jnp.sum(_apply_weights(per, weights), axis=-1)


def _nll(labels, preact, activation, weights):
    # DL4J aliases NEGATIVELOGLIKELIHOOD to MCXENT
    return _mcxent(labels, preact, activation, weights)


def _poisson(labels, preact, activation, weights):
    out = jnp.maximum(activation(preact), _EPS)
    per = out - labels * jnp.log(out)
    return jnp.sum(_apply_weights(per, weights), axis=-1)


def _cosine(labels, preact, activation, weights):
    out = activation(preact)
    dot = jnp.sum(out * labels, axis=-1)
    norm = jnp.linalg.norm(out, axis=-1) * jnp.linalg.norm(labels, axis=-1)
    return 1.0 - dot / jnp.maximum(norm, _EPS)


def _hinge(labels, preact, activation, weights):
    # labels in {-1, +1}
    out = activation(preact)
    return jnp.sum(_apply_weights(jnp.maximum(0.0, 1.0 - labels * out), weights), axis=-1)


def _squared_hinge(labels, preact, activation, weights):
    out = activation(preact)
    return jnp.sum(_apply_weights(jnp.maximum(0.0, 1.0 - labels * out) ** 2, weights), axis=-1)


MCXENT = _register("mcxent", _mcxent)
NEGATIVELOGLIKELIHOOD = _register("negativeloglikelihood", _nll)
XENT = _register("xent", _xent)
MSE = _register("mse", _mse)
SQUARED_LOSS = _register("squared_loss", _sse)
MEAN_ABSOLUTE_ERROR = _register("mean_absolute_error", _mae)
L1 = _register("l1", _l1)
L2 = _register("l2", _sse)
MEAN_ABSOLUTE_PERCENTAGE_ERROR = _register("mean_absolute_percentage_error", _mape)
MEAN_SQUARED_LOGARITHMIC_ERROR = _register("mean_squared_logarithmic_error", _msle)
KL_DIVERGENCE = _register("kl_divergence", _kld)
RECONSTRUCTION_CROSSENTROPY = _register("reconstruction_crossentropy", _xent)
POISSON = _register("poisson", _poisson)
COSINE_PROXIMITY = _register("cosine_proximity", _cosine)
HINGE = _register("hinge", _hinge)
SQUARED_HINGE = _register("squared_hinge", _squared_hinge)
