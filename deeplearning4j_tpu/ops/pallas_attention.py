"""Pallas flash-attention forward kernel — the accelerated-kernel stage.

Role in the framework (SURVEY §7 stage 4): the reference accelerates hot
layer math through optional cuDNN helpers discovered at runtime
(nn/layers/convolution/ConvolutionLayer.java:68-79 reflective load,
deeplearning4j-cuda/CudnnConvolutionHelper.java:54), validated by
helper-vs-stock comparison tests (deeplearning4j-cuda/src/test/). The TPU
equivalent: most ops lower optimally through XLA already, but attention is
the documented exception — the stock softmax(QK^T)V program materialises the
[B, H, T, T] score matrix in HBM, so at long T it is HBM-bandwidth-bound.
This kernel computes attention with the online-softmax (flash) recurrence:
K/V stream through VMEM in blocks, scores never leave the chip, O(T) memory
instead of O(T^2).

Scope: forward pass, optionally causal, no key-padding mask (callers fall
back to the stock path when a mask is present — see
SelfAttentionLayer.forward's helper switch, the AlgoMode analog). Backward
runs the stock XLA gradient via jax.custom_vjp with recompute, so training
gets the memory/speed win on the forward leg and bit-identical gradients to
the stock path.

Parity contract (the cuDNN-test pattern): tests/test_pallas_attention.py
compares kernel output and gradients against ``scaled_dot_attention`` in
interpret mode on CPU; bench.py measures the TPU win at T=2048.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _attn_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale: float,
                     causal: bool, block_q: int, block_k: int, seq_len: int):
    """One (batch*head, q-block) program: stream K/V blocks with the online
    softmax recurrence. q_ref: [block_q, d]; k_ref/v_ref: [T, d] (VMEM);
    o_ref: [block_q, d]."""
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * sm_scale
    d = q.shape[-1]
    nk = seq_len // block_k
    if causal:
        # blocks strictly above the diagonal contribute nothing: the last
        # key block needed is the one containing column (iq+1)*block_q - 1
        nk_eff = jnp.minimum(jnp.int32(nk),
                             ((iq + 1) * block_q - 1) // block_k + 1)
    else:
        nk_eff = nk

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 0)
                    + iq * block_q)
            cols = (jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 1)
                    + i * block_k)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (acc, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    B, H, T, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    qf = q.reshape(B * H, T, d)
    kf = k.reshape(B * H, T, d)
    vf = v.reshape(B * H, T, d)
    kernel = functools.partial(
        _attn_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=T)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, T, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, T, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, T, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, T, d)


DEFAULT_BLOCK = 512  # tuned on v5e: T=2048 1.5x, T=4096 2.9x over stock


def supports(q_shape, *, mask, block_q: int = DEFAULT_BLOCK,
             block_k: int = DEFAULT_BLOCK) -> bool:
    """Whether the kernel handles this case (callers fall back otherwise).
    Blocks are clamped to T, so the only requirement is divisibility."""
    if mask is not None or len(q_shape) != 4:
        return False
    T = q_shape[2]
    return T % min(block_q, T) == 0 and T % min(block_k, T) == 0


def flash_attention(q, k, v, *, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK,
                    block_k: int = DEFAULT_BLOCK, interpret=None):
    """softmax(q k^T / sqrt(d)) v with the flash recurrence.

    q/k/v: [B, H, T, d], T divisible by the (T-clamped) block sizes.
    ``interpret=None`` auto-selects interpreter mode off-TPU (so the same
    call works in the CPU test mesh). Gradients: stock XLA attention vjp on
    recomputed forward (jax.custom_vjp)."""
    T = q.shape[2]
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fwd = functools.partial(_flash_forward, causal=causal, block_q=block_q,
                            block_k=block_k, interpret=interpret)

    @jax.custom_vjp
    def attn(q, k, v):
        return fwd(q, k, v)

    def attn_fwd(q, k, v):
        return fwd(q, k, v), (q, k, v)

    def attn_bwd(res, g):
        from deeplearning4j_tpu.nn.conf.layers.attention import (
            scaled_dot_attention,
        )

        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: scaled_dot_attention(q_, k_, v_,
                                                    causal=causal),
            q, k, v)
        return vjp(g)

    attn.defvjp(attn_fwd, attn_bwd)
    return attn(q, k, v)
