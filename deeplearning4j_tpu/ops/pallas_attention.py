"""Pallas flash-attention forward kernel — the accelerated-kernel stage.

Role in the framework (SURVEY §7 stage 4): the reference accelerates hot
layer math through optional cuDNN helpers discovered at runtime
(nn/layers/convolution/ConvolutionLayer.java:68-79 reflective load,
deeplearning4j-cuda/CudnnConvolutionHelper.java:54), validated by
helper-vs-stock comparison tests (deeplearning4j-cuda/src/test/). The TPU
equivalent: most ops lower optimally through XLA already, but attention is
the documented exception — the stock softmax(QK^T)V program materialises the
[B, H, T, T] score matrix in HBM, so at long T it is HBM-bandwidth-bound.
This kernel computes attention with the online-softmax (flash) recurrence:
K/V stream through VMEM in blocks, scores never leave the chip, O(T) memory
instead of O(T^2).

Scope: forward + backward, optionally causal, optional [B, T] key-padding
mask (per-batch key-validity row broadcast over heads — the same
semantics as the stock path; round 5 closed the last helper-vs-stock
routing gap). The
backward is the standard flash recompute-by-block scheme (dq kernel over
q-blocks streaming K/V; dk/dv kernel over k-blocks streaming Q/dO), so
long-T *training* keeps O(T) memory — scores are rebuilt from the saved
row-logsumexp L and never materialise in HBM.

Parity contract (the cuDNN-test pattern): tests/test_pallas_attention.py
compares kernel output and gradients against ``scaled_dot_attention`` in
interpret mode on CPU; bench.py measures the TPU win at T=2048.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _causal_mask(s, iq, ik, block_q, block_k):
    rows = (jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            + iq * block_q)
    cols = (jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            + ik * block_k)
    return jnp.where(rows >= cols, s, NEG_INF)


def _attn_fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale: float,
                     causal: bool, has_mask: bool, block_q: int,
                     block_k: int, seq_len: int):
    """One (batch*head, q-block) program: stream K/V blocks with the online
    softmax recurrence. q_ref: [block_q, d]; k_ref/v_ref: [T, d] (VMEM);
    o_ref: [block_q, d]; lse_ref: [block_q, 1] row logsumexp (saved for the
    backward recompute). With ``has_mask``, mask_ref is a [1, T] f32 key
    validity row (shared by all heads of the batch)."""
    if has_mask:
        mask_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * sm_scale
    d = q.shape[-1]
    nk = seq_len // block_k
    if causal:
        # blocks strictly above the diagonal contribute nothing: the last
        # key block needed is the one containing column (iq+1)*block_q - 1
        nk_eff = jnp.minimum(jnp.int32(nk),
                             ((iq + 1) * block_q - 1) // block_k + 1)
    else:
        nk_eff = nk

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, iq, i, block_q, block_k)
        if has_mask:
            # Mosaic requires lane-dim dynamic slices provably 128-aligned;
            # flash_attention guarantees block_k % 128 == 0 (or one block)
            # whenever a mask is present. != 0 matches the stock path's
            # mask.astype(bool) semantics (any nonzero = valid).
            km = (mask_ref[:] if block_k == seq_len
                  else mask_ref[:, pl.ds(i * block_k, block_k)])
            s = jnp.where(km != 0, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (acc, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, None]


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_forward(q, k, v, key_mask, *, causal: bool, block_q: int,
                   block_k: int, interpret: bool):
    B, H, T, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    qf = q.reshape(B * H, T, d)
    kf = k.reshape(B * H, T, d)
    vf = v.reshape(B * H, T, d)
    has_mask = key_mask is not None
    kernel = functools.partial(
        _attn_fwd_kernel, sm_scale=sm_scale, causal=causal,
        has_mask=has_mask, block_q=block_q, block_k=block_k, seq_len=T)
    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, T, d), lambda b, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, T, d), lambda b, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if has_mask:
        # [B, 1, T]: one validity row per batch, shared across its heads
        # (program b belongs to batch b // H)
        in_specs.append(pl.BlockSpec(
            (None, 1, T), lambda b, i: (b // H, 0, 0),
            memory_space=pltpu.VMEM))
        args.append(key_mask.astype(jnp.float32).reshape(B, 1, T))
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, d), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, T, d), lse


def _attn_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    sm_scale: float, causal: bool, has_mask: bool,
                    block_q: int, block_k: int, seq_len: int):
    """dQ for one (batch*head, q-block): stream K/V, recompute P from the
    saved logsumexp, accumulate dS K. All VMEM-resident, f32 accumulation."""
    if has_mask:
        mask_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * sm_scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:].astype(jnp.float32)          # [block_q, 1]
    delta = delta_ref[:].astype(jnp.float32)      # [block_q, 1]
    d = q.shape[-1]
    nk = seq_len // block_k
    if causal:
        nk_eff = jnp.minimum(jnp.int32(nk),
                             ((iq + 1) * block_q - 1) // block_k + 1)
    else:
        nk_eff = nk

    def body(i, dq):
        k_blk = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, iq, i, block_q, block_k)
        if has_mask:
            km = (mask_ref[:] if block_k == seq_len
                  else mask_ref[:, pl.ds(i * block_k, block_k)])
            s = jnp.where(km != 0, s, NEG_INF)
        p = jnp.exp(s - lse)                      # normalized probabilities
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk_eff, body, jnp.zeros((block_q, d),
                                                      jnp.float32))
    dq_ref[:] = (dq * sm_scale).astype(dq_ref.dtype)


def _attn_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     *rest, sm_scale: float, causal: bool, has_mask: bool,
                     block_q: int, block_k: int, seq_len: int):
    """dK/dV for one (batch*head, k-block): stream Q/dO blocks, recompute
    P^T, accumulate dV = P^T dO and dK = dS^T Q * scale."""
    if has_mask:
        mask_ref, dk_ref, dv_ref = rest  # mask_ref: [1, block_k]
    else:
        dk_ref, dv_ref = rest
    ik = pl.program_id(1)
    k_blk = k_ref[:].astype(jnp.float32)          # [block_k, d]
    v_blk = v_ref[:].astype(jnp.float32)
    d = k_blk.shape[-1]
    nq = seq_len // block_q
    if causal:
        # q-blocks strictly above (before) this k-block's diagonal see none
        # of its columns: start at the block containing row ik*block_k
        iq0 = (ik * block_k) // block_q
    else:
        iq0 = 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32) \
            * sm_scale
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        delta = delta_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, i, ik, block_q, block_k)
        if has_mask:
            s = jnp.where(mask_ref[:] != 0, s, NEG_INF)
        p = jnp.exp(s - lse)                      # [block_q, block_k]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    zeros = jnp.zeros((k_blk.shape[0], d), jnp.float32)
    dk, dv = jax.lax.fori_loop(iq0, nq, body, (zeros, zeros))
    # dk = dS^T (q * sm_scale): q was loaded pre-scaled, no extra factor
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_backward(q, k, v, o, lse, do, key_mask, *, causal: bool,
                    block_q: int, block_k: int, interpret: bool):
    B, H, T, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    flat = lambda a: a.reshape(B * H, T, d)
    qf, kf, vf, dof = flat(q), flat(k), flat(v), flat(do)
    # D_i = dO_i . O_i — one fused elementwise-reduce in XLA, O(T d) reads
    delta = jnp.sum(dof.astype(jnp.float32)
                    * flat(o).astype(jnp.float32), axis=-1, keepdims=True)
    has_mask = key_mask is not None
    if has_mask:
        mf = key_mask.astype(jnp.float32).reshape(B, 1, T)

    blk_q = pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    blk_q1 = pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0),
                          memory_space=pltpu.VMEM)
    blk_k = pl.BlockSpec((None, block_k, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    full = pl.BlockSpec((None, T, d), lambda b, i: (b, 0, 0),
                        memory_space=pltpu.VMEM)
    full1 = pl.BlockSpec((None, T, 1), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM)
    # program b belongs to batch b // H; dq streams ALL key columns (full
    # mask row), dkv sees only its own k-block's columns
    mask_full = pl.BlockSpec((None, 1, T), lambda b, i: (b // H, 0, 0),
                             memory_space=pltpu.VMEM)
    mask_blk = pl.BlockSpec((None, 1, block_k), lambda b, i: (b // H, 0, i),
                            memory_space=pltpu.VMEM)

    dq_in = [blk_q, full, full, blk_q, blk_q1, blk_q1]
    dq_args = [qf, kf, vf, dof, lse, delta]
    if has_mask:
        dq_in.append(mask_full)
        dq_args.append(mf)
    dq = pl.pallas_call(
        functools.partial(_attn_dq_kernel, sm_scale=sm_scale, causal=causal,
                          has_mask=has_mask, block_q=block_q,
                          block_k=block_k, seq_len=T),
        grid=(B * H, T // block_q),
        in_specs=dq_in,
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct((B * H, T, d), q.dtype),
        interpret=interpret,
    )(*dq_args)

    dkv_in = [full, blk_k, blk_k, full, full1, full1]
    dkv_args = [qf, kf, vf, dof, lse, delta]
    if has_mask:
        dkv_in.append(mask_blk)
        dkv_args.append(mf)
    dk, dv = pl.pallas_call(
        functools.partial(_attn_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, has_mask=has_mask, block_q=block_q,
                          block_k=block_k, seq_len=T),
        grid=(B * H, T // block_k),
        in_specs=dkv_in,
        out_specs=[blk_k, blk_k],
        out_shape=[jax.ShapeDtypeStruct((B * H, T, d), k.dtype),
                   jax.ShapeDtypeStruct((B * H, T, d), v.dtype)],
        interpret=interpret,
    )(*dkv_args)

    unflat = lambda a: a.reshape(B, H, T, d)
    return unflat(dq), unflat(dk), unflat(dv)


DEFAULT_BLOCK = 512  # tuned on v5e: T=2048 1.5x, T=4096 2.9x over stock

# Each program holds full K and V [T, d] blocks in VMEM as f32 (~2*T*d*4
# bytes) plus the q/o blocks and accumulators; cap T*d so long sequences
# fall back to stock instead of crashing. Limit set EMPIRICALLY on v5e:
# T=4096, d=128 (T*d = 2^19) compiles (training needs the vjp block_q
# shrink below); T=8192, d=128 (2^20) fails scoped-VMEM even forward-only.
VMEM_SEQ_ELEMS_LIMIT = 1 << 19  # inclusive T * d ceiling (4096 * 128)


def supports(q_shape, *, mask, dtype=jnp.float32,
             block_q: int = DEFAULT_BLOCK,
             block_k: int = DEFAULT_BLOCK, backend: str | None = None) -> bool:
    """Whether the ``auto`` helper should route here (callers fall back to
    the stock XLA path otherwise). Declines when:

    - a key mask is present whose shape is not the [B, T] per-batch key
      validity row the kernels understand (round 5: masked workloads no
      longer force the stock path);
    - dtype is wider than float32 — the kernel casts to and accumulates in
      f32, so a float64 network would silently lose precision (breaks
      gradchecks); bf16/f16 inputs are fine (they gain precision);
    - the backend is not TPU — off-TPU the kernel runs in interpret mode,
      orders of magnitude slower than stock (``helper='pallas'`` still
      forces it, which is what the parity tests use);
    - T*d exceeds the VMEM ceiling (full K/V live in VMEM per program);
    - T is not divisible by the (T-clamped) block sizes.
    """
    if len(q_shape) != 4:
        return False
    if mask is not None and tuple(getattr(mask, "shape", ())) != \
            (q_shape[0], q_shape[2]):
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16),
                                jnp.dtype(jnp.float16)):
        return False
    if backend is None:
        backend = jax.default_backend()
    if backend != "tpu":
        return False
    T, d = q_shape[2], q_shape[3]
    if T * d > VMEM_SEQ_ELEMS_LIMIT:
        return False
    return T % min(block_q, T) == 0 and T % min(block_k, T) == 0


def flash_attention(q, k, v, *, causal: bool = False, mask=None,
                    block_q: int = DEFAULT_BLOCK,
                    block_k: int = DEFAULT_BLOCK, interpret=None):
    """softmax(q k^T / sqrt(d)) v with the flash recurrence.

    q/k/v: [B, H, T, d], T divisible by the (T-clamped) block sizes.
    ``mask``: optional [B, T] key-validity row (1 = attend, 0 = pad),
    broadcast over heads — same semantics as ``scaled_dot_attention``.
    ``interpret=None`` auto-selects interpreter mode off-TPU (so the same
    call works in the CPU test mesh). Gradients: Pallas recompute-by-block
    backward (dq / dk+dv kernels) from the saved row-logsumexp — O(T)
    memory for training too, unlike a stock-XLA vjp which would
    re-materialise the [B,H,T,T] score matrix in HBM."""
    T = q.shape[2]
    d = q.shape[3]
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if mask is not None:
        B = q.shape[0]
        if tuple(mask.shape) != (B, T):
            raise ValueError(
                f"key mask shape {tuple(mask.shape)} != (B, T) = "
                f"({B}, {T}) — a [B, T] key-validity row is required")
        # the in-kernel mask row is dynamically sliced on the LANE dim,
        # which Mosaic only compiles when the slice start is provably a
        # multiple of 128 — force a conforming block_k (or one full-row
        # block; VMEM already holds the full K/V so [1, T] is free)
        if block_k != T and (block_k % 128 or T % block_k):
            block_k = next((c for c in range(min(block_k, T) // 128 * 128,
                                             0, -128) if T % c == 0), T)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fwd = functools.partial(_flash_forward, causal=causal, block_q=block_q,
                            block_k=block_k, interpret=interpret)
    # The DIFFERENTIATED forward compiles in a jvp context where XLA's
    # scoped-VMEM accounting is tighter: at T=4096, d=128 the default
    # block_q=512 exceeds the 16 MiB limit by ~84 KiB (measured OOM) while
    # the primal-only call compiles fine. Shrink block_q for the vjp
    # forward only — the primal path keeps the faster big block (measured:
    # fwd 512/512 4.60 ms vs 256/512 5.26 ms; training 256/512 11.3 ms
    # where 512/512 cannot compile at all).
    vjp_block_q = block_q
    if T * d >= (1 << 19) and block_q > 256 and T % 256 == 0:
        # only when 256 keeps the grid covering T exactly — a non-divisor
        # would silently drop tail rows; shapes the shrink cannot help
        # keep the old block and fail loudly at compile instead
        vjp_block_q = 256
    vjp_fwd = functools.partial(_flash_forward, causal=causal,
                                block_q=vjp_block_q, block_k=block_k,
                                interpret=interpret)
    bwd = functools.partial(_flash_backward, causal=causal,
                            block_q=vjp_block_q, block_k=block_k,
                            interpret=interpret)

    if mask is None:
        @jax.custom_vjp
        def attn(q, k, v):
            return fwd(q, k, v, None)[0]

        def attn_fwd(q, k, v):
            o, lse = vjp_fwd(q, k, v, None)
            return o, (q, k, v, o, lse)

        def attn_bwd(res, g):
            q, k, v, o, lse = res
            return bwd(q, k, v, o, lse, g, None)

        attn.defvjp(attn_fwd, attn_bwd)
        return attn(q, k, v)

    m = jnp.asarray(mask, jnp.float32)  # float: a bool cotangent is invalid

    @jax.custom_vjp
    def attn_m(q, k, v, m):
        return fwd(q, k, v, m)[0]

    def attn_m_fwd(q, k, v, m):
        o, lse = vjp_fwd(q, k, v, m)
        return o, (q, k, v, m, o, lse)

    def attn_m_bwd(res, g):
        q, k, v, m, o, lse = res
        dq, dk, dv = bwd(q, k, v, o, lse, g, m)
        return dq, dk, dv, jnp.zeros_like(m)

    attn_m.defvjp(attn_m_fwd, attn_m_bwd)
    return attn_m(q, k, v, m)
