"""k-NN REST server (reference: deeplearning4j-nearestneighbor-server)."""

from deeplearning4j_tpu.nearestneighbors.server import NearestNeighborsServer

__all__ = ["NearestNeighborsServer"]
