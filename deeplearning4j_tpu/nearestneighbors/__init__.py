"""REST k-NN service (reference: deeplearning4j-nearestneighbor-server).

``DeviceBruteForceIndex`` and ``EmbeddingIndex`` are re-exported lazily so
host-only VPTree users never pay the jax import.
"""

from deeplearning4j_tpu.nearestneighbors.server import NearestNeighborsServer

__all__ = ["DeviceBruteForceIndex", "EmbeddingIndex",
           "NearestNeighborsServer"]


def __getattr__(name):
    if name == "DeviceBruteForceIndex":
        from deeplearning4j_tpu.nearestneighbors.brute import (
            DeviceBruteForceIndex,
        )

        return DeviceBruteForceIndex
    if name == "EmbeddingIndex":
        from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex

        return EmbeddingIndex
    raise AttributeError(name)
