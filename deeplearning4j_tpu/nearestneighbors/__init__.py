"""REST k-NN service (reference: deeplearning4j-nearestneighbor-server).

``DeviceBruteForceIndex`` is re-exported lazily so host-only VPTree users
never pay the jax import.
"""

from deeplearning4j_tpu.nearestneighbors.server import NearestNeighborsServer

__all__ = ["DeviceBruteForceIndex", "NearestNeighborsServer"]


def __getattr__(name):
    if name == "DeviceBruteForceIndex":
        from deeplearning4j_tpu.nearestneighbors.brute import (
            DeviceBruteForceIndex,
        )

        return DeviceBruteForceIndex
    raise AttributeError(name)
