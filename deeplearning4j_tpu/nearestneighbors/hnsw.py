"""HNSW graph index: the VPTree lineage's navigable-small-world form.

The reference retrieval stack ships a host VPTree
(``clustering/vptree/``) — a metric tree whose query walk is
inherently sequential and pointer-chasing, which is exactly why
brute.py inverted it into one device matmul. HNSW is the modern
incarnation of the same host-side idea: a layered proximity graph
(Malkov & Yashunin) where a query greedily descends geometric levels
to a good entry point, then runs an ef-bounded best-first beam on the
bottom layer. Search cost is O(ef · m · log N) distance rows instead
of O(N), so at the 10M+ point where even an IVF probe's candidate
gather is heavy, the graph walk answers from a few thousand rows.

This implementation is deliberately plain numpy — deterministic
(seeded geometric level draws, stable neighbor selection) so an index
rebuilt from the same points answers bit-identically, host-resident
(it composes with the int8/mesh *device* stores as an alternative, not
a layer), and served behind ``EmbeddingIndex``'s identical
``submit()``/coalescer surface with ``knn_recall`` as the
first-class acceptance gauge.

Distances: euclidean, or cosine on pre-normalized rows (the caller —
``EmbeddingIndex._build_store`` — normalizes once at build, exactly as
the flat/IVF stores do).
"""

from __future__ import annotations

import numpy as np

__all__ = ["HNSWGraph"]


class HNSWGraph:
    """Deterministic numpy HNSW over [N, D] f32 vectors.

    ``m`` is the per-node degree target (layer 0 keeps ``2m``);
    ``ef_construction`` bounds the insert-time beam. ``search_batch``
    mirrors the device kernels' contract: (distances [Q, k],
    indices [Q, k]) nearest-first, distances euclidean (sqrt'd) or
    cosine, padded with +inf/-1 when the graph holds fewer than k
    points."""

    def __init__(self, vectors, *, metric: str = "euclidean", m: int = 16,
                 ef_construction: int = 64, seed: int = 0):
        if metric not in ("euclidean", "cosine"):
            raise ValueError(f"metric must be euclidean|cosine, got {metric}")
        if int(m) < 2:
            raise ValueError(f"m must be >= 2, got {m}")
        self.vectors = np.ascontiguousarray(vectors, np.float32)
        if self.vectors.ndim != 2 or self.vectors.shape[0] < 1:
            raise ValueError("vectors must be a non-empty [N, D] array")
        self.metric = metric
        self.m = int(m)
        self.m0 = 2 * self.m
        self.ef_construction = max(int(ef_construction), self.m)
        n = self.vectors.shape[0]
        rng = np.random.RandomState(seed)
        # geometric level draws, all up front — insertion order plus
        # these levels fully determine the graph
        ml = 1.0 / np.log(self.m)
        u = rng.random_sample(n)
        self._node_level = np.minimum(
            (-np.log(np.maximum(u, 1e-12)) * ml).astype(np.int64), 31)
        self.levels = int(self._node_level.max()) + 1
        # adjacency per level: [n, cap] int32, -1 padded
        self._nbr = [np.full((n, self.m0 if lv == 0 else self.m), -1,
                             np.int32) for lv in range(self.levels)]
        self._nbr_cnt = [np.zeros(n, np.int32) for _ in range(self.levels)]
        self._entry = 0
        self._entry_level = int(self._node_level[0])
        for i in range(1, n):
            self._insert(i)

    @property
    def nbytes(self) -> int:
        return int(self.vectors.nbytes
                   + sum(a.nbytes for a in self._nbr)
                   + sum(a.nbytes for a in self._nbr_cnt))

    # ------------------------------------------------------------ distance
    def _dist_rows(self, q: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """Squared-euclidean (or cosine) distance of one query to a
        candidate row set — the single vectorized primitive every walk
        step reduces to."""
        v = self.vectors[rows]
        if self.metric == "cosine":
            return np.maximum(1.0 - v @ q, 0.0)
        diff = v - q[None, :]
        return np.einsum("nd,nd->n", diff, diff)

    # ------------------------------------------------------------- insert
    def _greedy_step(self, q: np.ndarray, ep: int, lv: int) -> int:
        """Greedy descent on one level: hop to the nearest neighbor
        until no neighbor improves."""
        cur = ep
        cur_d = float(self._dist_rows(q, np.array([cur]))[0])
        while True:
            cnt = self._nbr_cnt[lv][cur]
            if cnt == 0:
                return cur
            rows = self._nbr[lv][cur, :cnt]
            d = self._dist_rows(q, rows)
            j = int(np.argmin(d))
            if d[j] >= cur_d:
                return cur
            cur = int(rows[j])
            cur_d = float(d[j])

    def _beam(self, q: np.ndarray, ep: int, ef: int, lv: int):
        """Best-first beam of width ``ef`` on one level; returns
        (ids, dists) sorted nearest-first."""
        visited = {ep}
        d0 = float(self._dist_rows(q, np.array([ep]))[0])
        cand = [(d0, ep)]           # frontier, nearest popped first
        best = [(d0, ep)]           # result beam, kept sorted
        while cand:
            j = min(range(len(cand)), key=lambda i: cand[i][0])
            cd, cid = cand.pop(j)
            if cd > best[-1][0] and len(best) >= ef:
                break
            cnt = self._nbr_cnt[lv][cid]
            if cnt == 0:
                continue
            rows = self._nbr[lv][cid, :cnt]
            fresh = np.array([r for r in rows if int(r) not in visited],
                             np.int64)
            if fresh.size == 0:
                continue
            visited.update(int(r) for r in fresh)
            d = self._dist_rows(q, fresh)
            bound = best[-1][0]
            for dd, rr in zip(d, fresh):
                dd = float(dd)
                if len(best) < ef or dd < bound:
                    cand.append((dd, int(rr)))
                    best.append((dd, int(rr)))
                    best.sort()
                    if len(best) > ef:
                        best.pop()
                    bound = best[-1][0]
        ids = np.array([b[1] for b in best], np.int64)
        return ids, np.array([b[0] for b in best], np.float32)

    def _link(self, lv: int, a: int, b: int) -> None:
        """Add edge a->b, evicting a's farthest neighbor at capacity
        (stable: ties keep the earlier edge)."""
        cap = self._nbr[lv].shape[1]
        cnt = int(self._nbr_cnt[lv][a])
        if cnt < cap:
            self._nbr[lv][a, cnt] = b
            self._nbr_cnt[lv][a] = cnt + 1
            return
        rows = np.concatenate([self._nbr[lv][a, :cnt], [b]]).astype(np.int64)
        d = self._dist_rows(self.vectors[a], rows)
        keep = np.argsort(d, kind="stable")[:cap]
        self._nbr[lv][a, :cap] = rows[keep]

    def _insert(self, i: int) -> None:
        q = self.vectors[i]
        lv_i = int(self._node_level[i])
        ep = self._entry
        for lv in range(self._entry_level, lv_i, -1):
            ep = self._greedy_step(q, ep, lv)
        for lv in range(min(lv_i, self._entry_level), -1, -1):
            ids, _d = self._beam(q, ep, self.ef_construction, lv)
            take = ids[:self.m0 if lv == 0 else self.m]
            for t in take:
                t = int(t)
                self._link(lv, i, t)
                self._link(lv, t, i)
            ep = int(ids[0])
        if lv_i > self._entry_level:
            self._entry = i
            self._entry_level = lv_i

    # ------------------------------------------------------------- search
    def search(self, query, k: int, *, ef: int = 64):
        """(distances [k], indices [k]) nearest-first for one query."""
        q = np.asarray(query, np.float32).ravel()
        if self.metric == "cosine":
            q = q / max(float(np.linalg.norm(q)), 1e-12)
        ep = self._entry
        for lv in range(self._entry_level, 0, -1):
            ep = self._greedy_step(q, ep, lv)
        ids, d = self._beam(q, ep, max(int(ef), k), 0)
        ids, d = ids[:k], d[:k]
        if self.metric != "cosine":
            d = np.sqrt(d)
        if ids.size < k:
            pad = k - ids.size
            ids = np.concatenate([ids, np.full(pad, -1, np.int64)])
            d = np.concatenate([d, np.full(pad, np.inf, np.float32)])
        return d.astype(np.float32), ids.astype(np.int32)

    def search_batch(self, queries, k: int, *, ef: int = 64):
        """(distances [Q, k], indices [Q, k]) — the device kernels'
        exact return contract, so ``EmbeddingIndex``'s completer slices
        it untouched."""
        q = np.atleast_2d(np.asarray(queries, np.float32))
        d = np.empty((q.shape[0], k), np.float32)
        idx = np.empty((q.shape[0], k), np.int32)
        for r in range(q.shape[0]):
            d[r], idx[r] = self.search(q[r], k, ef=ef)
        return d, idx
