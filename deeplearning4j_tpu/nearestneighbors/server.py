"""REST k-NN service over a VPTree.

Reference: deeplearning4j-nearestneighbor-server
(server/NearestNeighborsServer.java + NearestNeighbor.java — Play REST,
base64 NDArray payloads). Here: stdlib http.server + JSON vectors (no
base64-NDArray legacy), same endpoints in spirit:

- POST /knn        {"k": 3, "point": [..]}          -> single query
- POST /knnVector  {"k": 3, "points": [[..], ..]}   -> batched (device path)
- GET  /status     -> {"points": N, "dims": D}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


class NearestNeighborsServer:
    """``backend="vptree"`` (host, reference-style pruning tree) or
    ``backend="device"`` (exact brute force: one MXU matmul + top_k per
    query batch — the TPU-idiomatic index, see brute.py)."""

    def __init__(self, points, port: int = 0, metric: str = "euclidean",
                 backend: str = "vptree"):
        points = np.asarray(points)
        self.shape = points.shape
        if backend == "vptree":
            self.tree = VPTree(np.asarray(points, np.float64),
                               metric=metric)
        elif backend == "device":
            from deeplearning4j_tpu.nearestneighbors.brute import (
                DeviceBruteForceIndex,
            )

            # the index keeps its own f32 device copy; no host copy pinned
            self.tree = DeviceBruteForceIndex(points, metric=metric)
        else:
            raise ValueError(
                f"backend must be vptree|device, got '{backend}'")
        self.backend = backend
        self._port = port
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/status":
                    self._json({"points": int(server.shape[0]),
                                "dims": int(server.shape[1])})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n))
                except json.JSONDecodeError:
                    self._json({"error": "bad json"}, 400)
                    return
                k = int(req.get("k", 1))
                if self.path == "/knn":
                    res = server.tree.search(np.asarray(req["point"]), k)
                    self._json({"results": [
                        {"index": i, "distance": d} for d, i in res]})
                elif self.path == "/knnVector":
                    batches = server.tree.search_batch(
                        np.asarray(req["points"]), k)
                    self._json({"results": [
                        [{"index": i, "distance": d} for d, i in b]
                        for b in batches]})
                else:
                    self._json({"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
