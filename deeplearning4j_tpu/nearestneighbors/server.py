"""REST k-NN service with the full production serving posture.

Reference: deeplearning4j-nearestneighbor-server
(server/NearestNeighborsServer.java + NearestNeighbor.java — Play REST,
base64 NDArray payloads). Here: stdlib http.server + JSON vectors (no
base64-NDArray legacy), same endpoints in spirit plus the serving tier:

- POST /knn        {"k": 3, "point": [..]}          -> single query
                   (or {"points": [[..], ..]} for a batch)
- POST /knnVector  {"k": 3, "points": [[..], ..]}   -> batched
- POST /encode     {"docs": [[..], ..], "add": true} -> encode (+store)
- GET  /status     -> {"points": N, "dims": D}       (back-compat shape)
- GET  /stats      -> serving + index counters
- GET  /metrics    -> Prometheus exposition

Three backends share the surface: ``vptree`` (host, reference-style
pruning tree), ``device`` (exact brute force, brute.py), and ``index``
(EmbeddingIndex, index.py — coalesced submits, int8/IVF/mesh stores).
All of them get the hardened HTTP layer (KerasBackendServer's posture):
malformed/ragged payloads, non-numeric or non-positive k, and dims
mismatches return structured 400s; bodies over ``max_body_bytes`` are
discarded unbuffered and answered 413; the resilience taxonomy maps to
429/503/504. The accept pump is a supervised ``ServingLoop`` tick, not a
raw thread, so the HTTP front end rides the same lifecycle (and chaos)
as every other server in the repo.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.metrics.exposition import CONTENT_TYPE, render_text
from deeplearning4j_tpu.metrics.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.resilience import (CircuitOpen,
                                                    DeadlineExceeded,
                                                    ServerOverloaded,
                                                    TransientDispatchError)
from deeplearning4j_tpu.parallel.runtime import (LoopClosed, LoopCrashed,
                                                 ServingLoop, supervisor)

#: typed serving failure -> (HTTP status, wire label). Order matters for
#: subclass matching (first isinstance wins).
_STATUS = {
    DeadlineExceeded: (504, "DeadlineExceeded"),
    ServerOverloaded: (429, "ServerOverloaded"),
    CircuitOpen: (503, "CircuitOpen"),
    TransientDispatchError: (503, "TransientDispatch"),
    LoopCrashed: (503, "Restarting"),
    LoopClosed: (503, "ShuttingDown"),
}


class _HttpError(Exception):
    """Validation failure carrying its HTTP status + wire label."""

    def __init__(self, status: int, label: str, detail: str):
        super().__init__(detail)
        self.status = status
        self.label = label
        self.detail = detail


class NearestNeighborsServer:
    """``backend="vptree"`` (host, reference-style pruning tree),
    ``backend="device"`` (exact brute force: one MXU matmul + top_k per
    query batch), or ``backend="index"`` (EmbeddingIndex: coalesced
    submits, f32/int8 store, optional IVF partitions and mesh sharding,
    full resilience posture). Passing ``index=`` adopts a pre-built
    EmbeddingIndex (and implies ``backend="index"``)."""

    # the accept pump (ServingLoop tick) reads the httpd handle
    # lock-free between rounds; stop() swaps it out under ``_lock``
    _LOOP_OWNED = ("_httpd",)
    _LOOP_LOCK = "_lock"

    def __init__(self, points=None, port: int = 0,
                 metric: str = "euclidean", backend: str = "vptree", *,
                 index=None, encoder=None, store: str = "f32",
                 partitions: Optional[int] = None, nprobe: int = 8,
                 mesh=None, max_body_bytes: int = 8 << 20,
                 default_deadline_s: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 **index_kwargs):
        if index is not None:
            backend = "index"
        if backend == "vptree":
            from deeplearning4j_tpu.clustering.vptree import VPTree

            self.tree = VPTree(np.asarray(points, np.float64),
                               metric=metric)
        elif backend == "device":
            from deeplearning4j_tpu.nearestneighbors.brute import (
                DeviceBruteForceIndex,
            )

            # the index keeps its own f32 device copy; no host copy pinned
            self.tree = DeviceBruteForceIndex(points, metric=metric)
        elif backend == "index":
            self.tree = None
        else:
            raise ValueError(
                f"backend must be vptree|device|index, got '{backend}'")
        self.backend = backend
        self._own_index = index is None
        if backend == "index":
            if index is None:
                from deeplearning4j_tpu.nearestneighbors.index import (
                    EmbeddingIndex,
                )

                index = EmbeddingIndex(points, metric, store=store,
                                       encoder=encoder, mesh=mesh,
                                       partitions=partitions, nprobe=nprobe,
                                       **index_kwargs)
            self.index = index
        else:
            self.index = None
        if points is not None:
            points = np.asarray(points)
            self.shape = points.shape
        else:
            self.shape = None
        self.max_body_bytes = int(max_body_bytes)
        self.default_deadline_s = default_deadline_s
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._m_requests = self.metrics.counter(
            "knn_http_requests_total", "HTTP requests received")
        self._m_errors = self.metrics.counter(
            "knn_http_errors_total", "HTTP requests answered non-2xx")
        self._m_http_latency = self.metrics.histogram(
            "knn_http_latency_ms", "request receive-to-response latency")
        self._port = port
        self._httpd = None
        self._loop: Optional[ServingLoop] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------- queries
    def _status_dims(self):
        if self.backend == "index":
            return self.index.n_points, self.index.dims
        return int(self.shape[0]), int(self.shape[1])

    def knn(self, queries, k: int, deadline_s: Optional[float] = None):
        """(distances [Q, k'], indices [Q, k']) with k' = min(k, N) — the
        uniform query core behind /knn and /knnVector. The index backend
        goes through the coalescer (so concurrent HTTP handlers merge
        into one device dispatch); vptree/device answer synchronously."""
        q = np.atleast_2d(np.asarray(queries, np.float32))
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        if self.backend == "index":
            fut = self.index.submit(q, k, deadline_s=deadline_s)
            return fut.result(
                None if deadline_s is None else deadline_s + 30.0)
        n, _d = self._status_dims()
        k = min(int(k), n)
        if self.backend == "device":
            return self.tree.search_batch_arrays(q, k)
        batches = self.tree.search_batch(q, k)
        d = np.asarray([[p[0] for p in b] for b in batches], np.float64)
        idx = np.asarray([[p[1] for p in b] for b in batches], np.int64)
        return d, idx

    def stats(self) -> dict:
        n, d = self._status_dims()
        out = {"backend": self.backend, "points": n, "dims": d,
               "requests": int(self._m_requests.value),
               "errors": int(self._m_errors.value)}
        if self.index is not None:
            out["index"] = self.index.stats()
        return out

    def metrics_text(self) -> str:
        """Prometheus exposition over the server registry and (when
        distinct) the index's — deduped by identity so a shared registry
        renders once."""
        sources = [({}, self.metrics)]
        if self.index is not None \
                and self.index.metrics is not self.metrics:
            sources.append(({}, self.index.metrics))
        return render_text(sources)

    # ------------------------------------------------------------ handlers
    def _check_k(self, req) -> int:
        k = req.get("k", 1)
        if isinstance(k, bool) or not isinstance(k, (int, float)) \
                or (isinstance(k, float) and not k.is_integer()):
            raise _HttpError(400, "BadRequest",
                             f"k must be a positive integer, got {k!r}")
        k = int(k)
        if k < 1:
            raise _HttpError(400, "BadRequest", f"k must be >= 1, got {k}")
        return k

    def _check_vectors(self, req, field: str, ndim: int) -> np.ndarray:
        if field not in req:
            raise _HttpError(400, "BadRequest",
                             f"missing required field '{field}'")
        try:
            arr = np.asarray(req[field], dtype=np.float32)
        except (TypeError, ValueError) as e:
            raise _HttpError(400, "BadRequest",
                             f"'{field}' must be rectangular numeric "
                             f"rows: {e}") from e
        if arr.ndim != ndim or arr.size == 0:
            raise _HttpError(400, "BadRequest",
                             f"'{field}' must be a non-empty "
                             f"{ndim}-d array, got shape {arr.shape}")
        _n, dims = self._status_dims()
        if arr.shape[-1] != dims:
            raise _HttpError(400, "BadRequest",
                             f"dims mismatch: index is D={dims}, "
                             f"got D={arr.shape[-1]}")
        return arr

    def _handle_knn(self, req: dict) -> dict:
        k = self._check_k(req)
        if "point" in req:
            q = self._check_vectors(req, "point", 1)[None, :]
        else:
            q = self._check_vectors(req, "points", 2)
        d, idx = self.knn(q, k, req.get("deadline_s"))
        results = [[{"index": int(i), "distance": float(dd)}
                    for dd, i in zip(dr, ir)] for dr, ir in zip(d, idx)]
        if "point" in req:
            return {"results": results[0]}
        return {"results": results}

    def _handle_knn_vector(self, req: dict) -> dict:
        k = self._check_k(req)
        q = self._check_vectors(req, "points", 2)
        d, idx = self.knn(q, k, req.get("deadline_s"))
        return {"results": [[{"index": int(i), "distance": float(dd)}
                             for dd, i in zip(dr, ir)]
                            for dr, ir in zip(d, idx)]}

    def _handle_encode(self, req: dict) -> dict:
        if self.backend != "index":
            raise _HttpError(400, "BadRequest",
                             "/encode requires backend='index'")
        field = "docs" if "docs" in req else "points"
        if field not in req:
            raise _HttpError(400, "BadRequest",
                             "missing required field 'docs'")
        try:
            docs = np.asarray(req[field], dtype=np.float32)
        except (TypeError, ValueError) as e:
            raise _HttpError(400, "BadRequest",
                             f"'{field}' must be numeric rows: {e}") from e
        if docs.ndim == 1:
            docs = docs[None, :]
        vecs = self.index.encode(docs)
        added = 0
        if req.get("add"):
            added = docs.shape[0]
            self.index.add(vecs)
        return {"vectors": vecs.tolist(), "added": added}

    # ----------------------------------------------------------- lifecycle
    @property
    def port(self) -> int:
        httpd = self._httpd
        return httpd.server_address[1] if httpd else self._port

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_body(self) -> bytes:
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    raise _HttpError(400, "BadRequest",
                                     "bad Content-Length") from None
                if n > server.max_body_bytes:
                    # unbuffered chunked discard: drain the wire without
                    # ever materializing the oversized body
                    left = n
                    while left > 0:
                        chunk = self.rfile.read(min(left, 1 << 16))
                        if not chunk:
                            break
                        left -= len(chunk)
                    raise _HttpError(
                        413, "BodyTooLarge",
                        f"body of {n} bytes exceeds max_body_bytes="
                        f"{server.max_body_bytes}")
                return self.rfile.read(n)

            def do_GET(self):
                server._m_requests.inc()
                if self.path == "/status":
                    n, d = server._status_dims()
                    self._json({"points": n, "dims": d})
                elif self.path == "/stats":
                    self._json(server.stats())
                elif self.path == "/metrics":
                    body = server.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    server._m_errors.inc()
                    self._json({"error": "NotFound",
                                "detail": "no such endpoint"}, 404)

            def do_POST(self):
                t0 = time.monotonic()
                server._m_requests.inc()
                try:
                    body = self._read_body()
                    try:
                        req = json.loads(body)
                    except json.JSONDecodeError as e:
                        raise _HttpError(400, "BadRequest",
                                         f"bad json: {e}") from e
                    if not isinstance(req, dict):
                        raise _HttpError(400, "BadRequest",
                                         "body must be a JSON object")
                    if self.path == "/knn":
                        out = server._handle_knn(req)
                    elif self.path == "/knnVector":
                        out = server._handle_knn_vector(req)
                    elif self.path == "/encode":
                        out = server._handle_encode(req)
                    else:
                        raise _HttpError(404, "NotFound",
                                         "no such endpoint")
                except _HttpError as e:
                    server._m_errors.inc()
                    self._json({"error": e.label, "detail": e.detail},
                               e.status)
                except tuple(_STATUS) as e:
                    server._m_errors.inc()
                    code, label = next(s for c, s in _STATUS.items()
                                       if isinstance(e, c))
                    self._json({"error": label, "detail": str(e)}, code)
                except (KeyError, TypeError, ValueError, OSError) as e:
                    server._m_errors.inc()
                    self._json({"error": "BadRequest", "detail": str(e)},
                               400)
                except Exception as e:  # noqa: BLE001 — structured 500
                    server._m_errors.inc()
                    self._json({"error": "InternalError",
                                "detail": f"{type(e).__name__}: {e}"}, 500)
                else:
                    self._json(out)
                finally:
                    server._m_http_latency.observe(
                        (time.monotonic() - t0) * 1e3)

        with self._lock:
            if self._httpd is not None:
                return self.port
            httpd = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
            httpd.daemon_threads = True
            # bounded accept wait: the supervised tick re-checks loop
            # state every handle_request() return
            httpd.timeout = 0.1
            self._httpd = httpd
            loop = ServingLoop("knn-http", tick=self._http_tick)
            self._loop = loop
        loop.start()
        supervisor().watch(loop, on_death=self._on_http_death, restart=True)
        return self.port

    def _http_tick(self) -> bool:
        """Accept pump: one bounded-wait accept per tick. Hosted on a
        supervised ServingLoop so the HTTP front end shares the uniform
        lifecycle (drain/close/chaos) instead of a raw daemon thread."""
        httpd = self._httpd
        if httpd is None:
            return False  # stop() swapped the handle out: exit cleanly
        httpd.handle_request()
        return True

    def _on_http_death(self, loop: ServingLoop, exc: BaseException) -> bool:
        return self._httpd is not None  # restart unless stopping

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the accept pump, close the socket, and (when this server
        built its own index) close the index. Idempotent."""
        with self._lock:
            loop, httpd = self._loop, self._httpd
            self._loop = None
            self._httpd = None
        if loop is not None:
            loop.close(timeout)
        if httpd is not None:
            httpd.server_close()
        if self._own_index and self.index is not None:
            self.index.close(timeout)

    def close(self, timeout: float = 10.0) -> None:
        """ReplicaFleet-compatible alias for ``stop``."""
        self.stop(timeout)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
