"""Device brute-force k-NN: one matmul + top_k on the accelerator.

The reference serves k-NN from a host VPTree
(nearestneighbor-server/NearestNeighbor.java over clustering/vptree/
VPTree.java:39). A VPTree prunes distance computations — the right trade
on a CPU. On TPU the idiomatic index is the opposite: compute ALL
distances as one [Q, N] matmul on the MXU and take ``lax.top_k`` — no
tree, no branching, batch-friendly, and exact. For N in the millions this
is a single well-fused device program per query batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


@partial(jax.jit, static_argnames=("k", "metric"))
def _knn(points, sq_norms, queries, *, k: int, metric: str):
    # NOTE: deliberately NOT shared with clustering.VPTree — that is the
    # host/float64 reference-style index that must work without a device;
    # this is the device kernel (same split as plot.Tsne exact vs BH).
    if metric == "cosine":
        # points arrive pre-normalized from __init__ (uploaded once)
        q = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1,
                                                  keepdims=True), 1e-12)
        dists = jnp.maximum(1.0 - q @ points.T, 0.0)
    else:  # euclidean: ||q||^2 - 2 q.p + ||p||^2, computed via the matmul
        qn = jnp.sum(queries * queries, axis=1, keepdims=True)
        dots = queries @ points.T
        dists = jnp.maximum(qn - 2.0 * dots + sq_norms[None, :], 0.0)
    neg, idx = jax.lax.top_k(-dists, k)
    d = -neg
    if metric != "cosine":
        d = jnp.sqrt(d)
    return d, idx


class DeviceBruteForceIndex:
    """Exact k-NN with device-resident points (uploaded once).

    >>> index = DeviceBruteForceIndex(points)
    >>> dists, idx = index.search_batch_arrays(queries, k=5)
    """

    def __init__(self, points, metric: str = "euclidean"):
        if metric not in ("euclidean", "cosine"):
            raise ValueError(f"metric must be euclidean|cosine, got {metric}")
        pts = np.asarray(points, np.float32)
        if pts.ndim != 2:
            raise ValueError(f"points must be [N, D], got {pts.shape}")
        self.metric = metric
        self.points = jnp.asarray(pts)
        if metric == "cosine":
            # normalize ONCE at upload; per-query work stays O(Q*D)
            self.points = self.points / jnp.maximum(
                jnp.linalg.norm(self.points, axis=1, keepdims=True), 1e-12)
        self._sq_norms = jnp.sum(self.points * self.points, axis=1)

    @property
    def n_points(self) -> int:
        return int(self.points.shape[0])

    def search_batch_arrays(self, queries, k: int):
        """(distances [Q, k], indices [Q, k]) as numpy, nearest first.

        Query batch size AND k are padded up to power-of-two buckets
        before the jitted kernel so streams of varying sizes compile
        O(log Q_max * log k_max) programs, not one per distinct (Q, k)
        (an XLA compile inside a REST handler is a multi-hundred-ms
        stall); results are sliced back to the requested shape.

        ``k`` above the point count is clamped to N (the result contract
        is min(k, N) columns — ``lax.top_k`` with k > N would fail inside
        the jit); ``k < 1`` or a non-integer k raises ``ValueError``
        BEFORE dispatch (k=0 would silently bucket up to 1 and negative
        k would mis-slice the result)."""
        if isinstance(k, bool) or not isinstance(k, (int, np.integer)):
            raise ValueError(f"k must be a positive integer, got {k!r}")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        q = np.atleast_2d(np.asarray(queries, np.float32))
        k = min(int(k), self.n_points)
        Q = q.shape[0]
        bucket = 1 << max(Q - 1, 0).bit_length()  # next power of two
        if bucket != Q:
            q = np.concatenate([q, np.zeros((bucket - Q, q.shape[1]),
                                            np.float32)])
        kb = min(1 << max(k - 1, 0).bit_length(), self.n_points)
        d, idx = _knn(self.points, self._sq_norms, jnp.asarray(q),
                      k=kb, metric=self.metric)
        return np.asarray(d)[:Q, :k], np.asarray(idx)[:Q, :k]

    def search_batch(self, queries, k: int) -> list:
        """VPTree.search_batch-compatible: per query a list of
        (distance, index) pairs, nearest first."""
        d, idx = self.search_batch_arrays(queries, k)
        return [[(float(dd), int(ii)) for dd, ii in zip(dr, ir)]
                for dr, ir in zip(d, idx)]

    def search(self, point, k: int):
        """[(distance, index), ...] for one query — VPTree.search shape."""
        d, idx = self.search_batch_arrays(np.asarray(point)[None, :], k)
        return [(float(dd), int(ii)) for dd, ii in zip(d[0], idx[0])]
