"""EmbeddingIndex: device-resident vector store + coalesced k-NN serving.

The reference retrieval stack is a host VPTree behind a Play REST server
(deeplearning4j-nearestneighbor-server). The TPU-idiomatic inversion
(brute.py) computes ALL distances as one [Q, N] MXU matmul + ``lax.top_k``
— this module grows that kernel into a serving subsystem:

* **Encode**: documents batch-encode through any encoder exposing
  ``output(x)`` (``ParallelInference`` over a net, a zoo model) or a plain
  callable, straight into the store.
* **Store**: device-resident, f32 or absmax per-ROW int8
  (optimize/quantize.py's recipe with the row as the "channel"); the
  dequant is fused into the query matmul's epilogue —
  ``(q @ P_q.T) * scale`` — so the vectors stay int8 in memory
  (~(4D+4)/(D+8)x capacity at a fixed byte budget) and are widened on the
  fly. Optionally mesh-sharded over the points axis: the distance matmul
  and ``top_k`` partition over the mesh and GSPMD inserts the single
  on-device merge, so stores bigger than one chip's HBM still answer with
  one program.
* **IVF**: a partitioned variant for the 10M+-vector regime — k-means
  centroids (clustering/), an nprobe-limited candidate gather, and an
  exact re-rank of the gathered candidates, recall-gated ≥0.95 vs exact
  in tests and the ``knn_serve`` bench. With a mesh the centroids train
  SHARDED (per-device assign sweeps, GSPMD all-reduce centroid updates)
  and the cells become device-RESIDENT: each device probes its own
  local cells and gathers candidates locally (``_probe_local_rank``
  under ``shard_map``), so a 10M-vector int8 store splits across the
  mesh and a query moves k candidates per device — never a cell list —
  over ICI.
* **HNSW**: ``store="hnsw"`` swaps in a graph index (the reference's
  ``clustering/vptree`` lineage, navigable-small-world form): greedy
  descent through geometric levels + an ef-bounded beam at layer 0,
  host-resident, behind the identical ``submit()``/coalescer surface
  with recall as a first-class gauge.
* **Serve**: ``submit() -> Future`` queries flow through a background
  coalescer (``ServingLoop``) mirroring ParallelInference's: N one-row
  submits become ONE fused matmul+top_k dispatch, bucketed pow2 on both
  the query rows and k (optimize/bucketing.py) so batch churn compiles
  O(log Q * log k) programs, zero retrace after warmup. The full serving
  posture rides along: Deadline/RetryPolicy/CircuitBreaker/
  AdmissionController, supervised loops, MetricsRegistry counters and the
  ``knn_latency_ms`` histogram, and the ReplicaFleet duck-type
  (submit/drain/close/stats) so an index replica rides health-weighted
  routing and chaos like every other server.

The exact f32 unsharded path delegates to brute.py's ``_knn`` with the
identical pad/bucket arithmetic, so it is byte-identical to
``DeviceBruteForceIndex`` by construction (asserted in
tests/test_knn_serve.py). The int8 store is built by deterministic host
arithmetic, so a drained/restarted index rebuilt from the same points
answers bit-identically.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.metrics.registry import MetricsRegistry
from deeplearning4j_tpu.nearestneighbors.brute import _knn
from deeplearning4j_tpu.optimize.bucketing import BoundedCache
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, shard_map_compat
from deeplearning4j_tpu.parallel.resilience import (AdmissionController,
                                                    ChaosPolicy,
                                                    CircuitBreaker,
                                                    CircuitOpen, Deadline,
                                                    DeadlineExceeded,
                                                    RetryPolicy)
from deeplearning4j_tpu.parallel.runtime import (LoopClosed, LoopCrashed,
                                                 ServingLoop, supervisor)


# --------------------------------------------------------------------------
# device kernels
# --------------------------------------------------------------------------
# Every kernel returns (distances [Q, k], indices [Q, k]) nearest-first and
# keeps the whole candidate scoring + top_k on device. ``aux`` is one pad
# vector doing double duty: for euclidean it carries ||p||^2 (+inf on pad
# rows, so a padded row can never be selected); for cosine it is a plain
# 0/+inf bias added after the 1 - q.p term.

@partial(jax.jit, static_argnames=("k", "metric"))
def _knn_aux(points, aux, queries, *, k: int, metric: str):
    """f32 store with pad bias — the mesh-sharded flat path. When the
    operands are committed with a points-axis NamedSharding, the [Q, N]
    matmul and the top_k partition over the mesh and GSPMD inserts the
    single on-device merge."""
    if metric == "cosine":
        q = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1,
                                                  keepdims=True), 1e-12)
        dists = jnp.maximum(1.0 - q @ points.T, 0.0) + aux[None, :]
    else:
        qn = jnp.sum(queries * queries, axis=1, keepdims=True)
        dists = jnp.maximum(qn - 2.0 * (queries @ points.T) + aux[None, :],
                            0.0)
    neg, idx = jax.lax.top_k(-dists, k)
    d = -neg
    if metric != "cosine":
        d = jnp.sqrt(d)
    return d, idx


@partial(jax.jit, static_argnames=("k", "metric"))
def _knn_int8(qpoints, scales, aux, queries, *, k: int, metric: str):
    """int8 store: absmax per-row quantized points with the dequant fused
    into the query matmul's epilogue — ``(q @ P_q.T) * scale`` widens the
    int8 rows on the fly; they never exist as f32 in memory. For euclidean
    ``aux`` carries the DEQUANTIZED rows' ||p||^2 so the distances are
    exact distances to the reconstructed vectors."""
    if metric == "cosine":
        q = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1,
                                                  keepdims=True), 1e-12)
        dots = (q @ qpoints.T.astype(queries.dtype)) * scales[None, :]
        dists = jnp.maximum(1.0 - dots, 0.0) + aux[None, :]
    else:
        qn = jnp.sum(queries * queries, axis=1, keepdims=True)
        dots = (queries @ qpoints.T.astype(queries.dtype)) * scales[None, :]
        dists = jnp.maximum(qn - 2.0 * dots + aux[None, :], 0.0)
    neg, idx = jax.lax.top_k(-dists, k)
    d = -neg
    if metric != "cosine":
        d = jnp.sqrt(d)
    return d, idx


@partial(jax.jit, static_argnames=("k", "nprobe", "metric"))
def _knn_ivf(centroids, cbias, vecs, scales, laux, ids, queries, *,
             k: int, nprobe: int, metric: str):
    """IVF query: score the [Q, C] centroid distances, gather the
    ``nprobe`` nearest lists' vectors, exact re-rank the gathered
    candidates, and map the local top_k back to global ids — all one
    program. ``scales=None`` selects the f32-list trace; an int8 store
    passes the [C, M] per-row scales and the dequant rides the candidate
    matmul's epilogue exactly as in ``_knn_int8``.

    Probe selection is always euclidean-on-the-stored-rows: cosine stores
    arrive pre-normalized, where euclidean order == cosine order."""
    Qn = queries.shape[0]
    if metric == "cosine":
        q = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1,
                                                  keepdims=True), 1e-12)
        qn = jnp.ones((Qn, 1), queries.dtype)
    else:
        q = queries
        qn = jnp.sum(q * q, axis=1, keepdims=True)
    c2 = jnp.sum(centroids * centroids, axis=1)
    cd = qn - 2.0 * (q @ centroids.T) + c2[None, :] + cbias[None, :]
    _, probes = jax.lax.top_k(-cd, nprobe)                  # [Q, P]
    cand = jnp.take(vecs, probes, axis=0)                   # [Q, P, M, D]
    aux = jnp.take(laux, probes, axis=0).reshape(Qn, -1)    # [Q, P*M]
    gids = jnp.take(ids, probes, axis=0).reshape(Qn, -1)
    M = cand.shape[1] * cand.shape[2]
    flat = cand.reshape(Qn, M, -1).astype(queries.dtype)
    dots = jnp.einsum("qd,qmd->qm", q, flat)
    if scales is not None:
        dots = dots * jnp.take(scales, probes, axis=0).reshape(Qn, M)
    if metric == "cosine":
        dists = jnp.maximum(1.0 - dots, 0.0) + aux
    else:
        dists = jnp.maximum(qn - 2.0 * dots + aux, 0.0)
    neg, loc = jax.lax.top_k(-dists, k)
    d = -neg
    idx = jnp.take_along_axis(gids, loc, axis=1)
    if metric != "cosine":
        d = jnp.sqrt(d)
    return d, idx


@jax.jit
def _assign_chunk(x, centroids):
    """Nearest-centroid assignment for one build chunk (device, so the
    1M+-row assignment sweep is a handful of matmuls, not a host loop)."""
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(centroids * centroids, axis=1)
    d2 = xn - 2.0 * (x @ centroids.T) + c2[None, :]
    return jnp.argmin(d2, axis=1).astype(jnp.int32)


@jax.jit
def _kmeans_step(x, centroids):
    """One sharded Lloyd iteration: per-device nearest-centroid
    assignment, per-device partial sums, all-reduce centroid update.
    ``x`` arrives committed P(data, None) and ``centroids`` replicated,
    so GSPMD partitions the assign matmul and the ``oh.T @ x`` /
    count reductions over the mesh and inserts the all-reduce — the
    10M-row assign sweep never leaves its device. Empty clusters keep
    their previous centroid. Returns (new centroids, max shift)."""
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(centroids * centroids, axis=1)
    d2 = xn - 2.0 * (x @ centroids.T) + c2[None, :]
    assign = jnp.argmin(d2, axis=1)
    oh = jax.nn.one_hot(assign, centroids.shape[0], dtype=x.dtype)
    sums = oh.T @ x                          # [C, D] partial -> all-reduce
    cnts = jnp.sum(oh, axis=0)               # [C]
    newc = jnp.where(cnts[:, None] > 0.5,
                     sums / jnp.maximum(cnts, 1.0)[:, None], centroids)
    return newc, jnp.max(jnp.abs(newc - centroids))


def _probe_local_rank(centroids, cbias, vecs, scales, laux, ids, q, qn,
                      *, k: int, nprobe: int, metric: str):
    """Per-device IVF probe + gather + re-rank (the ``shard_map`` body;
    on the graftcheck hot list — pure jnp, no host syncs). Every operand
    except the replicated query block is this device's shard: probe the
    ``min(nprobe, local cells)`` nearest LOCAL cells, gather their
    vectors locally (no cross-device cell movement), exact re-rank to
    the local top-k, and pad to k with +inf/-1 so the caller's one
    on-device merge over the [Q, devices*k] concatenation is exact.
    Distances stay squared for euclidean — the merge applies the sqrt.

    Recall dominates the global-probe kernel's: any cell in the global
    top-``nprobe`` is in its home device's local top-``nprobe``, so the
    union candidate pool is a superset of the global pool."""
    Qn = q.shape[0]
    p = min(nprobe, centroids.shape[0])
    c2 = jnp.sum(centroids * centroids, axis=1)
    cd = qn - 2.0 * (q @ centroids.T) + c2[None, :] + cbias[None, :]
    _, probes = jax.lax.top_k(-cd, p)                        # [Q, p] local
    cand = jnp.take(vecs, probes, axis=0)                    # [Q, p, M, D]
    aux = jnp.take(laux, probes, axis=0).reshape(Qn, -1)
    gids = jnp.take(ids, probes, axis=0).reshape(Qn, -1)
    M = cand.shape[1] * cand.shape[2]
    flat = cand.reshape(Qn, M, -1).astype(q.dtype)
    dots = jnp.einsum("qd,qmd->qm", q, flat)
    if scales is not None:
        dots = dots * jnp.take(scales, probes, axis=0).reshape(Qn, M)
    if metric == "cosine":
        dists = jnp.maximum(1.0 - dots, 0.0) + aux
    else:
        dists = jnp.maximum(qn - 2.0 * dots + aux, 0.0)
    kk = min(k, M)
    neg, loc = jax.lax.top_k(-dists, kk)
    d = -neg
    lids = jnp.take_along_axis(gids, loc, axis=1)
    if kk < k:
        d = jnp.concatenate(
            [d, jnp.full((Qn, k - kk), jnp.inf, d.dtype)], axis=1)
        lids = jnp.concatenate(
            [lids, jnp.full((Qn, k - kk), -1, lids.dtype)], axis=1)
    return d, lids


def _make_probe_local(mesh, metric: str, quantized: bool):
    """Build the jitted probe-local IVF search for one (mesh, metric,
    store kind): ``shard_map`` over the cell axis with each device
    contributing its local top-k, merged by ONE on-device top_k over
    the [Q, devices*k] concatenation. Module-level + cached so store
    rebuilds (bulk adds) reuse the compiled programs — zero retrace."""
    store_specs = [P(DATA_AXIS, None), P(DATA_AXIS),
                   P(DATA_AXIS, None, None)]
    if quantized:
        store_specs.append(P(DATA_AXIS, None))               # scales
    store_specs += [P(DATA_AXIS, None), P(DATA_AXIS, None)]  # laux, ids
    in_specs = tuple(store_specs) + (P(None, None), P(None, None))
    out_specs = (P(None, DATA_AXIS), P(None, DATA_AXIS))

    @partial(jax.jit, static_argnames=("k", "nprobe"))
    def search(arrays, queries, *, k: int, nprobe: int):
        Qn = queries.shape[0]
        if metric == "cosine":
            q = queries / jnp.maximum(
                jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-12)
            qn = jnp.ones((Qn, 1), queries.dtype)
        else:
            q = queries
            qn = jnp.sum(q * q, axis=1, keepdims=True)

        def body(*ops):
            if quantized:
                c, cb, v, s, la, ii, qq, qqn = ops
            else:
                (c, cb, v, la, ii, qq, qqn), s = ops, None
            return _probe_local_rank(c, cb, v, s, la, ii, qq, qqn,
                                     k=k, nprobe=nprobe, metric=metric)

        sm = shard_map_compat(body, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs)
        d, ii = sm(*(tuple(arrays) + (q, qn)))   # [Q, devices*k] each
        neg, loc = jax.lax.top_k(-d, k)
        dd = -neg
        idx = jnp.take_along_axis(ii, loc, axis=1)
        if metric != "cosine":
            dd = jnp.sqrt(dd)
        return dd, idx

    return search


_PROBE_LOCAL_CACHE: dict = {}


def _probe_local_searcher(mesh, metric: str, quantized: bool):
    key = (mesh, metric, quantized)
    fn = _PROBE_LOCAL_CACHE.get(key)
    if fn is None:
        fn = _PROBE_LOCAL_CACHE[key] = _make_probe_local(
            mesh, metric, quantized)
    return fn


# --------------------------------------------------------------------------
# store construction (host-side, deterministic)
# --------------------------------------------------------------------------

def _quantize_rows(pts: np.ndarray):
    """Absmax per-ROW int8 (quantize_array's recipe with the row as the
    channel — each stored vector gets its own scale, so one outlier
    vector cannot crush every other row's resolution). Deterministic
    host arithmetic: rebuilding from the same points is bit-identical."""
    absmax = np.max(np.abs(pts), axis=1)
    scale = (absmax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(pts / safe[:, None]), -127, 127).astype(np.int8)
    return q, scale


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


class _Store:
    """One immutable device-store snapshot. ``add()`` builds a fresh
    snapshot and swaps the reference atomically, so the serving loops
    read a coherent store lock-free (EmbeddingIndex._LOOP_OWNED)."""

    __slots__ = ("variant", "n", "dim", "arrays", "nprobe", "n_lists",
                 "list_len", "spilled", "resident_bytes", "searcher",
                 "graph")

    def __init__(self, variant, n, dim, arrays, nprobe=0, n_lists=0,
                 list_len=0, spilled=0, searcher=None, graph=None):
        self.variant = variant      # exact | aux | int8 | ivf | hnsw
        self.n = n
        self.dim = dim
        self.arrays = arrays
        self.nprobe = nprobe
        self.n_lists = n_lists
        self.list_len = list_len
        self.spilled = spilled
        self.searcher = searcher    # probe-local jitted search (mesh IVF)
        self.graph = graph          # HNSWGraph (store="hnsw")
        self.resident_bytes = sum(int(a.nbytes) for a in arrays
                                  if a is not None)
        if graph is not None:
            self.resident_bytes += graph.nbytes


class _QueryRequest:
    """One submitted query batch: rows + the future its slice lands in,
    the k it asked for and the pow2 bucket kb it dispatches under (the
    coalesce signature, so only same-program requests merge)."""

    __slots__ = ("q", "k", "kb", "n", "future", "deadline", "t0")

    def __init__(self, q, k, kb, deadline: Optional[Deadline] = None):
        self.q = q
        self.k = k
        self.kb = kb
        self.n = q.shape[0]
        self.future: Future = Future()
        self.deadline = deadline
        self.t0 = time.monotonic()

    def signature(self):
        return (self.q.shape[1], self.kb)


class EmbeddingIndex:
    """Device-resident vector store with a coalescing query server.

    >>> index = EmbeddingIndex(points, store="int8")
    >>> d, i = index.search_batch_arrays(queries, k=5)     # sync
    >>> fut = index.submit(query_row, k=5)                 # coalesced
    >>> d, i = fut.result()

    ``store="f32"`` (default) is bit-identical to
    ``DeviceBruteForceIndex``; ``store="int8"`` trades exactness for
    ~3.3x capacity at D=32. ``partitions=C`` builds the IVF variant
    (k-means centroids, ``nprobe`` probed lists per query, exact
    re-rank). ``mesh`` shards the flat store (and the IVF lists) over
    the points axis. ``encoder`` is anything with ``output(x)`` — a
    ``ParallelInference`` over a net — or a plain callable; documents
    added via ``add_documents`` are batch-encoded through it."""

    # The store snapshot is read lock-free by the coalescer/completer
    # loops (and sync searchers); every off-loop write swaps it under
    # ``_lock`` (conc-loop-ownership, analysis/concurrency_rules.py).
    _LOOP_OWNED = ("_store",)
    _LOOP_LOCK = "_lock"

    def __init__(self, points=None, metric: str = "euclidean", *,
                 store: str = "f32", encoder=None, mesh=None,
                 partitions: Optional[int] = None, nprobe: int = 8,
                 list_cap: Optional[int] = None, train_sample: int = 65536,
                 kmeans_iters: int = 25, kmeans: str = "auto",
                 hnsw_m: int = 16, ef_construction: int = 64,
                 ef_search: int = 64, seed: int = 0,
                 max_batch: int = 64, max_wait_ms: float = 2.0,
                 inflight: int = 2, max_pending: int = 256,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 chaos: Optional[ChaosPolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 default_k: int = 10):
        if metric not in ("euclidean", "cosine"):
            raise ValueError(f"metric must be euclidean|cosine, got {metric}")
        if store not in ("f32", "int8", "hnsw"):
            raise ValueError(f"store must be f32|int8|hnsw, got {store}")
        if kmeans not in ("auto", "host", "sharded"):
            raise ValueError(
                f"kmeans must be auto|host|sharded, got {kmeans}")
        if kmeans == "sharded" and mesh is None:
            raise ValueError("kmeans='sharded' requires a mesh")
        if store == "hnsw" and (mesh is not None or partitions is not None):
            raise ValueError("store='hnsw' is host-resident: it composes "
                             "with neither mesh= nor partitions=")
        nprobe = int(nprobe)
        if nprobe < 1:
            raise ValueError(f"nprobe must be >= 1, got {nprobe}")
        self.metric = metric
        self.store_kind = store
        self.encoder = encoder
        self.mesh = mesh
        self.partitions = None if partitions is None else int(partitions)
        # over-probing beyond the partition count clamps at build time
        # (nprobe = min(nprobe, C)); under-probing below 1 is the typed
        # ValueError above
        self.nprobe = nprobe
        self.list_cap = list_cap
        self.train_sample = int(train_sample)
        self.kmeans_iters = int(kmeans_iters)
        self.kmeans = kmeans
        self.hnsw_m = int(hnsw_m)
        self.ef_construction = int(ef_construction)
        self.ef_search = int(ef_search)
        self.seed = int(seed)
        self.default_k = int(default_k)
        self.max_batch = int(max_batch)
        # the lever's fixed ceiling ("slots" in the tier_stats surface,
        # mirroring GenerationServer's compiled slot pool)
        self.max_batch_pool = self.max_batch
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.inflight = max(1, int(inflight))
        self.admission = AdmissionController(max_pending)
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = (None if breaker is False
                        else breaker if breaker is not None
                        else CircuitBreaker())
        self._dispatch = (chaos.wrap(self._dispatch_knn) if chaos is not None
                          else self._dispatch_knn)
        self._chaos = chaos
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._m_dispatches = self.metrics.counter(
            "knn_dispatches_total", "device search programs issued")
        self._m_rejected_circuit = self.metrics.counter(
            "knn_rejected_circuit_total",
            "submits fast-failed by the open breaker")
        self._m_retried = self.metrics.counter(
            "knn_retried_total", "dispatch retry attempts")
        self._m_expired = self.metrics.counter(
            "knn_expired_total", "queries expired before dispatch")
        self._m_completed = self.metrics.counter(
            "knn_completed_total", "query futures resolved with rows")
        self._m_failed = self.metrics.counter(
            "knn_failed_total", "query futures resolved with a typed error")
        self._m_latency = self.metrics.histogram(
            "knn_latency_ms", "submit-to-resolution latency")
        self._m_batch_rows = self.metrics.histogram(
            "knn_batch_rows", "query rows per coalesced dispatch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self._m_recall = self.metrics.gauge(
            "knn_recall", "last measured recall vs exact (1.0 = exact)")
        self.metrics.gauge("knn_pending", "queries in flight",
                           fn=lambda: self.admission.pending)
        self.metrics.gauge("knn_resident_bytes",
                           "device bytes held by the vector store",
                           fn=lambda: self.resident_bytes)
        self.metrics.gauge("knn_points", "vectors in the store",
                           fn=lambda: self.n_points)
        self.metrics.gauge("knn_breaker_open",
                           "0 closed / 0.5 half-open / 1 open",
                           fn=self._breaker_level)
        self._drain_cv = threading.Condition()
        self._draining = False
        self._coalescer: Optional[ServingLoop] = None
        self._completer: Optional[ServingLoop] = None
        self._outstanding: set = set()
        self._lock = threading.Lock()
        self._closed = False
        # distinct device programs requested (zero-retrace accounting:
        # batch churn must keep this O(log max_batch * log k), asserted
        # in tests and visible in stats())
        self._programs = BoundedCache()
        self._host: Optional[np.ndarray] = None
        self._store: Optional[_Store] = None
        if points is not None:
            self.add(points)

    # ------------------------------------------------------------- metrics
    def _breaker_level(self) -> float:
        if self.breaker is None:
            return 0.0
        return {"closed": 0.0, "half_open": 0.5,
                "open": 1.0}.get(self.breaker.state, 0.0)

    @property
    def n_points(self) -> int:
        st = self._store
        return 0 if st is None else st.n

    @property
    def dims(self) -> int:
        st = self._store
        return 0 if st is None else st.dim

    @property
    def resident_bytes(self) -> int:
        st = self._store
        return 0 if st is None else st.resident_bytes

    @property
    def dispatch_count(self) -> int:
        return int(self._m_dispatches.value)

    # ------------------------------------------------- autoscaler lever
    @property
    def active_slot_cap(self) -> int:
        """GenerationServer duck-type for FleetTierTarget: the knn
        tier's capacity knob is the coalescer's row cap."""
        return self.max_batch

    def set_active_slots(self, n: int) -> int:
        """Autoscaler lever (GenerationServer duck-type): moves the
        coalescer's ``max_batch`` row cap within [1, construction-time
        pool]. Bigger batches amortize the dispatch under load; smaller
        ones bound per-query latency."""
        self.max_batch = max(1, min(int(n), self.max_batch_pool))
        return self.max_batch

    # -------------------------------------------------------------- encode
    def encode(self, docs) -> np.ndarray:
        """Batch-encode documents into [N, D] f32 vectors through the
        attached encoder (``output(x)`` — e.g. ParallelInference — or a
        plain callable). With no encoder the docs ARE the vectors."""
        x = np.asarray(docs, np.float32)
        enc = self.encoder
        if enc is None:
            return np.atleast_2d(x)
        out = enc.output(x) if hasattr(enc, "output") else enc(x)
        out = np.asarray(out, np.float32)
        if out.ndim != 2:
            out = out.reshape(out.shape[0], -1)
        return out

    def add_documents(self, docs) -> np.ndarray:
        """Encode ``docs`` and add the vectors; returns them. Encoding
        runs outside the index lock (it may be a full sharded forward)."""
        vecs = self.encode(docs)
        self.add(vecs)
        return vecs

    def add(self, points) -> int:
        """Add [N, D] vectors: rebuild the (immutable) device store
        snapshot and swap it in atomically. Returns the new point count.
        IVF lists are rebuilt too — adds are a bulk-load operation here,
        not a hot path."""
        pts = np.atleast_2d(np.asarray(points, np.float32))
        if pts.ndim != 2:
            raise ValueError(f"points must be [N, D], got {pts.shape}")
        with self._lock:
            if self._closed:
                raise RuntimeError("EmbeddingIndex is closed")
            if self._host is not None:
                if pts.shape[1] != self._host.shape[1]:
                    raise ValueError(
                        f"dims mismatch: store is D={self._host.shape[1]}, "
                        f"got D={pts.shape[1]}")
                host = np.concatenate([self._host, pts])
            else:
                host = pts
            self._host = host
            self._store = self._build_store(host)
            return self._store.n

    # ------------------------------------------------------- store builder
    def _build_store(self, host: np.ndarray) -> _Store:
        n, d = host.shape
        pure = (self.store_kind == "f32" and self.mesh is None
                and self.partitions is None)
        if pure:
            # byte-identity path: identical upload arithmetic to
            # DeviceBruteForceIndex (jnp normalization included), and the
            # search side calls brute._knn with the same pad/bucket code
            points = jnp.asarray(host)
            if self.metric == "cosine":
                points = points / jnp.maximum(
                    jnp.linalg.norm(points, axis=1, keepdims=True), 1e-12)
            sq = jnp.sum(points * points, axis=1)
            return _Store("exact", n, d, (points, sq))
        pts = host
        if self.metric == "cosine":
            # normalize ONCE at build (host-side for the quantized /
            # padded variants; deterministic for bit-identical rebuilds)
            nrm = np.maximum(
                np.linalg.norm(pts, axis=1, keepdims=True), 1e-12)
            pts = (pts / nrm).astype(np.float32)
        if self.store_kind == "hnsw":
            return self._build_hnsw(pts)
        if self.partitions is not None:
            return self._build_ivf(pts)
        return self._build_flat(pts)

    def _build_hnsw(self, pts: np.ndarray) -> _Store:
        from deeplearning4j_tpu.nearestneighbors.hnsw import HNSWGraph

        n, d = pts.shape
        graph = HNSWGraph(pts, metric=self.metric, m=self.hnsw_m,
                          ef_construction=self.ef_construction,
                          seed=self.seed)
        return _Store("hnsw", n, d, (), graph=graph)

    def _put(self, a, spec=None):
        """Upload one store array, sharded over the points axis when a
        mesh is attached (committed shardings make every query program
        partition over the mesh with one on-device top_k merge)."""
        if self.mesh is None:
            return jnp.asarray(a)
        if spec is None:
            spec = P(DATA_AXIS) if a.ndim == 1 else \
                P(DATA_AXIS, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(self.mesh, spec))

    def _padded(self, pts: np.ndarray):
        """Pad the rows to the mesh multiple; returns (padded points,
        pad-aware aux vector) — aux carries ||p||^2 for euclidean and 0
        for cosine, +inf on pad rows so they can never be selected."""
        n, d = pts.shape
        npad = n
        if self.mesh is not None:
            m = int(self.mesh.devices.size)
            npad = -(-n // m) * m
        if npad != n:
            pts = np.concatenate([pts, np.zeros((npad - n, d), np.float32)])
        if self.metric == "cosine":
            aux = np.zeros(npad, np.float32)
        else:
            aux = np.sum(pts * pts, axis=1).astype(np.float32)
        aux[n:] = np.inf
        return pts, aux

    def _build_flat(self, pts: np.ndarray) -> _Store:
        n, d = pts.shape
        padded, aux = self._padded(pts)
        if self.store_kind == "int8":
            q, scale = _quantize_rows(padded)
            if self.metric == "euclidean":
                # exact ||p||^2 of the RECONSTRUCTED rows, so distances
                # are true distances to what the store actually holds
                deq = q.astype(np.float32) * scale[:, None]
                aux = np.where(np.isinf(aux), np.inf,
                               np.sum(deq * deq, axis=1)).astype(np.float32)
            return _Store("int8", n, d,
                          (self._put(q), self._put(scale), self._put(aux)))
        return _Store("aux", n, d, (self._put(padded), self._put(aux)))

    def _kmeans_sharded(self, sample: np.ndarray, C: int) -> np.ndarray:
        """Mesh-sharded centroid training: the training rows are
        committed P(data, None) and every Lloyd iteration is ONE
        ``_kmeans_step`` program — per-device assign sweep, GSPMD
        all-reduce centroid update. Deterministic init from ``seed``
        (real rows, never pad), host-synced shift test per iteration
        (build path, not serving). Row padding REPEATS real rows so the
        pad can never mint a phantom centroid."""
        n, d = sample.shape
        m = int(self.mesh.devices.size)
        npad = -(-n // m) * m
        if npad != n:
            sample = np.concatenate(
                [sample, sample[np.resize(np.arange(n), npad - n)]])
        rng = np.random.RandomState(self.seed)
        centroids = sample[rng.choice(n, C, replace=n < C)]
        xd = jax.device_put(
            sample, NamedSharding(self.mesh, P(DATA_AXIS, None)))
        cd = jax.device_put(
            np.ascontiguousarray(centroids, np.float32),
            NamedSharding(self.mesh, P(None, None)))
        for _ in range(self.kmeans_iters):
            cd, shift = _kmeans_step(xd, cd)
            if float(shift) <= 1e-6:
                break
        return np.asarray(cd, np.float32)

    def _build_ivf(self, pts: np.ndarray) -> _Store:
        from deeplearning4j_tpu.clustering.kmeans import KMeansClustering

        n, d = pts.shape
        C = max(1, min(self.partitions, n))
        rng = np.random.RandomState(self.seed)
        t = min(self.train_sample, n)
        sample = pts if t == n else pts[rng.choice(n, t, replace=False)]
        sharded = self.kmeans == "sharded" or (
            self.kmeans == "auto" and self.mesh is not None)
        if sharded and self.mesh is not None:
            centroids = self._kmeans_sharded(sample, C)
        else:
            km = KMeansClustering(C, max_iterations=self.kmeans_iters,
                                  seed=self.seed)
            km.apply_to(sample)
            centroids = np.asarray(km.centers, np.float32)
        # chunked device assignment: fixed pow2 chunk so the sweep is one
        # program regardless of N
        CH = min(65536, _pow2(n))
        assign = np.empty(n, np.int64)
        cdev = jnp.asarray(centroids)
        for s in range(0, n, CH):
            xb = pts[s:s + CH]
            m = xb.shape[0]
            if m != CH:
                xb = np.concatenate([xb, np.zeros((CH - m, d), np.float32)])
            assign[s:s + m] = np.asarray(
                _assign_chunk(jnp.asarray(xb), cdev))[:m]
        counts = np.bincount(assign, minlength=C)
        M = _pow2(max(int(counts.max()), 1))
        if self.list_cap is not None:
            M = min(M, _pow2(self.list_cap))
        spilled = int(np.maximum(counts - M, 0).sum())
        order = np.argsort(assign, kind="stable")
        # pad C to the mesh multiple with +inf-biased empty lists
        Cpad = C
        if self.mesh is not None:
            m = int(self.mesh.devices.size)
            Cpad = -(-C // m) * m
        quant = self.store_kind == "int8"
        ids = np.full((Cpad, M), -1, np.int32)
        if quant:
            # memory-lean 10M-point build: quantize cell by cell straight
            # into the preallocated int8 store — the f32 [C*M, D] copy and
            # its dequant transient never exist (peak extra = one cell)
            qvecs = np.zeros((Cpad, M, d), np.int8)
            scl = np.zeros((Cpad, M), np.float32)
            lsq = np.zeros((Cpad, M), np.float32)
        else:
            vecs = np.zeros((Cpad, M, d), np.float32)
        pos = 0
        for c in range(C):
            take = order[pos:pos + counts[c]][:M]
            pos += counts[c]
            ids[c, :len(take)] = take
            if len(take) == 0:
                continue
            if quant:
                qr, sr = _quantize_rows(pts[take])
                deq = qr.astype(np.float32) * sr[:, None]
                qvecs[c, :len(take)] = qr
                scl[c, :len(take)] = sr
                lsq[c, :len(take)] = np.sum(deq * deq, axis=1)
            else:
                vecs[c, :len(take)] = pts[take]
        if Cpad != C:
            centroids = np.concatenate(
                [centroids, np.zeros((Cpad - C, d), np.float32)])
        cbias = np.zeros(Cpad, np.float32)
        cbias[C:] = np.inf
        scales = None
        if quant:
            vdev = self._put(qvecs)
            scales = self._put(scl)
        else:
            lsq = np.sum(vecs * vecs, axis=2)
            vdev = self._put(vecs)
        if self.metric == "cosine":
            laux = np.zeros((Cpad, M), np.float32)
        else:
            laux = lsq.astype(np.float32)
        laux[ids < 0] = np.inf   # empty slots (and pad lists) never win
        nprobe = min(self.nprobe, C)
        searcher = None if self.mesh is None else _probe_local_searcher(
            self.mesh, self.metric, quant)
        return _Store("ivf", n, d,
                      (self._put(centroids), self._put(cbias), vdev, scales,
                       self._put(laux), self._put(ids)),
                      nprobe=nprobe, n_lists=C, list_len=M, spilled=spilled,
                      searcher=searcher)

    # ------------------------------------------------------------ dispatch
    def _bucket_kb(self, k: int, st: _Store) -> int:
        kb = min(_pow2(k), st.n)
        if st.variant == "ivf":
            # the re-rank pool is nprobe*M candidates; k must fit it
            kb = min(kb, st.nprobe * st.list_len)
        return kb

    def _check_query(self, queries, k):
        """Typed validation shared by both entries: returns (q [Q, D] f32,
        k clamped to N, kb). Raises ValueError before any device work."""
        st = self._store
        if st is None:
            raise ValueError("EmbeddingIndex is empty: add vectors first")
        if isinstance(k, bool) or not isinstance(k, (int, np.integer)):
            raise ValueError(f"k must be a positive integer, got {k!r}")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        q = np.atleast_2d(np.asarray(queries, np.float32))
        if q.ndim != 2:
            raise ValueError(f"queries must be [Q, D], got {q.shape}")
        if q.shape[1] != st.dim:
            raise ValueError(f"dims mismatch: store is D={st.dim}, "
                             f"got D={q.shape[1]}")
        k = min(int(k), st.n)
        return q, k, self._bucket_kb(k, st)

    def _dispatch_knn(self, x, kb):
        """Pad the query rows to the pow2 bucket and issue ONE device
        search program (async — the caller/completer strips the padding
        after the fetch). The pad/bucket arithmetic is byte-for-byte
        DeviceBruteForceIndex.search_batch_arrays's."""
        st = self._store
        if st is None:
            raise ValueError("EmbeddingIndex is empty: add vectors first")
        Q = x.shape[0]
        bucket = 1 << max(Q - 1, 0).bit_length()
        if bucket != Q:
            x = np.concatenate([x, np.zeros((bucket - Q, x.shape[1]),
                                            np.float32)])
        qd = jnp.asarray(x)
        if st.variant == "exact":
            points, sq = st.arrays
            self._record_program(("exact", bucket, kb))
            out = _knn(points, sq, qd, k=kb, metric=self.metric)
        elif st.variant == "aux":
            points, aux = st.arrays
            self._record_program(("aux", bucket, kb))
            out = _knn_aux(points, aux, qd, k=kb, metric=self.metric)
        elif st.variant == "int8":
            qpts, scales, aux = st.arrays
            self._record_program(("int8", bucket, kb))
            out = _knn_int8(qpts, scales, aux, qd, k=kb, metric=self.metric)
        elif st.variant == "hnsw":
            # host graph walk: returns numpy, so the completer's "fetch"
            # is a no-op copy — no device program, but the same bucketed
            # signature keys the ledger
            self._record_program(("hnsw", bucket, kb))
            out = st.graph.search_batch(x, kb, ef=self.ef_search)
        else:
            centroids, cbias, vecs, scales, laux, ids = st.arrays
            nprobe = min(max(st.nprobe, -(-kb // st.list_len)), st.n_lists)
            if st.searcher is not None:
                # probe-local mesh path: per-device cells, per-device
                # gathers, one cross-device top-k merge
                self._record_program(("ivf_local", bucket, kb, nprobe))
                out = st.searcher(
                    tuple(a for a in st.arrays if a is not None), qd,
                    k=kb, nprobe=nprobe)
            else:
                self._record_program(("ivf", bucket, kb, nprobe))
                out = _knn_ivf(centroids, cbias, vecs, scales, laux, ids,
                               qd, k=kb, nprobe=nprobe, metric=self.metric)
        self._m_dispatches.inc()
        return out

    def _record_program(self, key) -> None:
        if key not in self._programs:
            self._programs[key] = True

    # ---------------------------------------------------------- sync entry
    def search_batch_arrays(self, queries, k: int):
        """(distances [Q, k], indices [Q, k]) as numpy, nearest first —
        DeviceBruteForceIndex's contract (and, on the pure f32 path, its
        exact bytes)."""
        q, k, kb = self._check_query(queries, k)
        Q = q.shape[0]
        d, idx = self._dispatch_knn(q, kb)
        return np.asarray(d)[:Q, :k], np.asarray(idx)[:Q, :k]

    def search_batch(self, queries, k: int) -> list:
        """VPTree.search_batch-compatible: per query a list of
        (distance, index) pairs, nearest first."""
        d, idx = self.search_batch_arrays(queries, k)
        return [[(float(dd), int(ii)) for dd, ii in zip(dr, ir)]
                for dr, ir in zip(d, idx)]

    def search(self, point, k: int):
        """[(distance, index), ...] for one query — VPTree.search shape."""
        d, idx = self.search_batch_arrays(
            np.asarray(point, np.float32)[None, :], k)
        return [(float(dd), int(ii)) for dd, ii in zip(d[0], idx[0])]

    def measure_recall(self, queries, k: int = 10) -> float:
        """Recall@k of this store vs an exact f32 search over the same
        points (the IVF/int8 acceptance gate). Builds a temporary exact
        view from the host copy; publishes the ``knn_recall`` gauge."""
        with self._lock:
            host = self._host
        if host is None:
            raise ValueError("EmbeddingIndex is empty: add vectors first")
        q = np.atleast_2d(np.asarray(queries, np.float32))
        pts = jnp.asarray(host)
        if self.metric == "cosine":
            pts = pts / jnp.maximum(
                jnp.linalg.norm(pts, axis=1, keepdims=True), 1e-12)
        sq = jnp.sum(pts * pts, axis=1)
        kk = min(int(k), host.shape[0])
        _, exact = _knn(pts, sq, jnp.asarray(q), k=kk, metric=self.metric)
        exact = np.asarray(exact)
        _, got = self.search_batch_arrays(q, kk)
        hits = sum(len(np.intersect1d(exact[i], got[i]))
                   for i in range(q.shape[0]))
        recall = hits / float(exact.size)
        self._m_recall.set(recall)
        return recall

    # --------------------------------------------------------- async entry
    def submit(self, queries, k: Optional[int] = None, *,
               deadline_s: Optional[float] = None) -> Future:
        """Async k-NN: returns a Future of (distances [Q, k], indices
        [Q, k]). Concurrent submissions with the same (dims, k-bucket)
        signature are coalesced into ONE padded matmul+top_k dispatch and
        sliced back per caller; ``deadline_s``/admission/breaker behave
        exactly as ParallelInference.submit (typed DeadlineExceeded /
        ServerOverloaded / CircuitOpen, never a hang)."""
        q, k, kb = self._check_query(
            queries, self.default_k if k is None else k)
        with self._lock:
            if self._closed or self._draining:
                raise RuntimeError("EmbeddingIndex is closed"
                                   if self._closed else
                                   "EmbeddingIndex is draining")
            co = self._ensure_workers()
        if self.breaker is not None and not self.breaker.allow():
            self._m_rejected_circuit.inc()
            raise CircuitOpen("circuit breaker is open: recent dispatches "
                              "failed above threshold")
        self.admission.acquire()  # raises ServerOverloaded at watermark
        req = _QueryRequest(
            q, k, kb,
            None if deadline_s is None else Deadline(deadline_s))
        # single release point for admission + completion counters: fires
        # on EVERY resolution path, so pending can never leak
        req.future.add_done_callback(
            lambda f, t0=req.t0: self._on_done(f, t0))
        with self._lock:
            self._outstanding.add(req.future)
        try:
            co.put(req)
        except LoopClosed:
            with self._lock:
                closed = self._closed
            self._fail(req.future,
                       RuntimeError("EmbeddingIndex is closed") if closed
                       else LoopCrashed("knn-coalescer is restarting; "
                                        "resubmit the query"))
            return req.future
        with self._lock:
            closed = self._closed
        if closed and not req.future.done():
            self._fail(req.future, RuntimeError("EmbeddingIndex is closed"))
        return req.future

    def _on_done(self, fut: Future, t0: Optional[float] = None) -> None:
        with self._lock:
            self._outstanding.discard(fut)
        self.admission.release()
        if fut.exception() is None:
            self._m_completed.inc()
            if t0 is not None:
                self._m_latency.observe((time.monotonic() - t0) * 1e3)
        else:
            self._m_failed.inc()
        with self._drain_cv:
            self._drain_cv.notify_all()

    @staticmethod
    def _fail(future: Future, exc: Exception) -> None:
        try:
            future.set_exception(exc)
        except Exception:  # noqa: BLE001 — already resolved, either way
            pass

    # -------------------------------------------------------- runtime loops
    def _ensure_workers(self) -> ServingLoop:
        """Start the runtime loops once and return the coalescer. Caller
        holds ``self._lock`` (rank below the loop condition, so start/
        watch nest legally)."""
        if self._coalescer is None:
            completer = ServingLoop(
                "knn-completer", handler=self._knn_complete_loop,
                inbox_maxsize=self.inflight,
                on_leftover=self._fail_inflight_leftover,
                chaos=self._chaos)
            coalescer = ServingLoop(
                "knn-coalescer", handler=self._knn_coalesce_entry,
                on_leftover=self._fail_submit_leftover,
                chaos=self._chaos)
            self._completer = completer
            self._coalescer = coalescer
            completer.start()
            coalescer.start()
            sup = supervisor()
            sup.watch(completer, on_death=self._on_loop_death, restart=True)
            sup.watch(coalescer, on_death=self._on_loop_death, restart=True)
        return self._coalescer

    def _on_loop_death(self, loop: ServingLoop, exc: BaseException):
        with self._lock:
            victims = list(self._outstanding)
            closed = self._closed
        err = LoopCrashed(f"{loop.name} died with the query in flight: "
                          f"{exc!r}")
        for f in victims:
            if not f.done():
                self._fail(f, err)
        return not closed

    def _fail_submit_leftover(self, req) -> None:
        self._fail(req.future, RuntimeError("EmbeddingIndex is closed"))

    def _fail_inflight_leftover(self, item) -> None:
        _out, batch = item
        for r in batch:
            self._fail(r.future, RuntimeError("EmbeddingIndex is closed"))

    def _expire_if_dead(self, req) -> bool:
        if req.deadline is None or not req.deadline.expired():
            return False
        self._m_expired.inc()
        self._fail(req.future, DeadlineExceeded(
            f"query expired {-req.deadline.remaining() * 1e3:.1f} ms "
            "before dispatch"))
        return True

    @staticmethod
    def _flush_by(d) -> float:
        """Latest instant the assembly window may run to for a member
        with deadline ``d`` (a quarter of the remaining budget is
        reserved for the dispatch itself)."""
        return d.expires_at - 0.25 * max(0.0, d.remaining())

    def _knn_coalesce_entry(self, first):
        with self._lock:
            co, completer = self._coalescer, self._completer
        return self._knn_coalesce_once(first, co, completer)

    def _knn_coalesce_once(self, first, co: ServingLoop,
                           completer: ServingLoop):
        """Coalescer handler: assemble ONE batch starting from ``first``
        and dispatch it; a signature mismatch flushes early and is
        carried back as this worker's next head."""
        if self._expire_if_dead(first):
            return None
        head = None
        batch = [first]
        rows = first.n
        sig = first.signature()
        deadline = time.monotonic() + self.max_wait_s
        if first.deadline is not None:
            deadline = min(deadline, self._flush_by(first.deadline))
        while rows < self.max_batch:
            wait = deadline - time.monotonic()
            if wait <= 0:
                break
            try:
                nxt = co.get(timeout=wait)
            except queue.Empty:
                break
            if nxt.signature() != sig:
                head = nxt
                break
            if self._expire_if_dead(nxt):
                continue
            batch.append(nxt)
            rows += nxt.n
            if nxt.deadline is not None:
                deadline = min(deadline, self._flush_by(nxt.deadline))
        self._knn_dispatch_batch(batch, completer)
        return head

    def _count_retry(self, attempt, exc) -> None:
        self._m_retried.inc()

    def _knn_dispatch_batch(self, batch, completer: ServingLoop):
        batch = [r for r in batch if not self._expire_if_dead(r)]
        if not batch:
            return
        self._m_batch_rows.observe(sum(r.n for r in batch))
        earliest = min((r.deadline for r in batch if r.deadline is not None),
                       key=lambda d: d.expires_at, default=None)
        kb = batch[0].kb

        def attempt():
            try:
                out = self._dispatch(x, kb)  # async dispatch, no fetch
            except Exception:
                if self.breaker is not None:
                    self.breaker.record_failure()
                raise
            if self.breaker is not None:
                self.breaker.record_success()
            return out

        try:
            x = (batch[0].q if len(batch) == 1
                 else np.concatenate([r.q for r in batch]))
            out = self.retry.call(attempt, deadline=earliest,
                                  on_retry=self._count_retry)
        except Exception as e:  # noqa: BLE001 — surface on every future
            for r in batch:
                if not self._expire_if_dead(r):
                    self._fail(r.future, e)
            return
        while True:
            if completer.crashed is not None:
                err = LoopCrashed("knn-completer died with the batch in "
                                  "flight")
                for r in batch:
                    self._fail(r.future, err)
                return
            try:
                completer.put((out, batch), timeout=0.2)
                return
            except queue.Full:
                continue
            except LoopClosed:
                err = RuntimeError("EmbeddingIndex is closed")
                for r in batch:
                    self._fail(r.future, err)
                return

    @staticmethod
    def _fetch_pair(out):
        """THE single sanctioned device->host sync per coalesced batch,
        isolated from the HOT_FUNCTIONS-audited completer body so the
        analyzer proves no OTHER sync creeps into the loop."""
        d, idx = out
        return np.asarray(d), np.asarray(idx)

    def _knn_complete_loop(self, item):
        """Completer handler: one device fetch per coalesced batch,
        sliced back per caller (each future gets its own [n, k] rows,
        padding and k-bucket stripped)."""
        out, batch = item
        try:
            d, idx = self._fetch_pair(out)
        except Exception as e:  # noqa: BLE001
            for r in batch:
                self._fail(r.future, e)
            return None
        ofs = 0
        for r in batch:
            try:
                r.future.set_result((d[ofs:ofs + r.n, :r.k],
                                     idx[ofs:ofs + r.n, :r.k]))
            except Exception:  # noqa: BLE001 — lost a shutdown race
                pass
            ofs += r.n
        return None

    # ------------------------------------------------------------ lifecycle
    def stats(self) -> dict:
        """Serving + store counters, assembled entirely OUTSIDE the
        serving locks (every counter is a leaf-locked registry metric)."""
        st = self._store
        out = {"retried": int(self._m_retried.value),
               "expired": int(self._m_expired.value),
               "rejected_circuit": int(self._m_rejected_circuit.value),
               "completed": int(self._m_completed.value),
               "failed": int(self._m_failed.value),
               "dispatches": int(self._m_dispatches.value),
               "programs": len(self._programs),
               "points": 0 if st is None else st.n,
               "dims": 0 if st is None else st.dim,
               "store": self.store_kind,
               "variant": "empty" if st is None else st.variant,
               "resident_bytes": 0 if st is None else st.resident_bytes,
               "recall": float(self._m_recall.value)}
        if st is not None and st.variant == "ivf":
            out.update(partitions=st.n_lists, list_len=st.list_len,
                       nprobe=st.nprobe, spilled=st.spilled,
                       probe_local=st.searcher is not None)
        if st is not None and st.variant == "hnsw":
            out.update(hnsw_m=st.graph.m, ef_search=self.ef_search,
                       levels=st.graph.levels)
        out.update(
            accepted=self.admission.accepted,
            rejected=self.admission.rejected,
            pending=self.admission.pending,
            breaker_state=(self.breaker.state if self.breaker is not None
                           else "disabled"),
            # fleet tier_stats surface (FleetTierTarget's observation
            # keys): queue depth + the capacity lever's pool size
            queued=self.admission.pending,
            slots=self.max_batch_pool)
        return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop admitting submits while every in-flight
        query resolves. Sync searches keep working — drain is a serving
        pause, not a store teardown."""
        with self._lock:
            self._draining = True
            co, cm = self._coalescer, self._completer
        if co is not None:
            co.begin_drain()
        if cm is not None:
            cm.begin_drain()
        limit = None if timeout is None else time.monotonic() + timeout
        while True:
            # liveness read OUTSIDE _drain_cv (the loop condition ranks
            # below it and may never be acquired while it is held)
            dead = co is None or (co.alive_workers == 0
                                  and (cm is None
                                       or cm.alive_workers == 0))
            with self._drain_cv:
                if self.admission.pending == 0:
                    return True
                if dead:
                    return False
                wait = 0.2 if limit is None else min(
                    0.2, limit - time.monotonic())
                if wait <= 0:
                    return False
                self._drain_cv.wait(wait)

    def close(self, timeout: float = 30.0):
        """Drain, then stop both runtime loops. Idempotent and
        re-entrant; every admitted future resolves — with rows or a
        typed error — before close returns."""
        with self._lock:
            should_drain = not self._closed and self._coalescer is not None
        if should_drain:
            self.drain(timeout)
        with self._lock:
            self._closed = True
            co, cm = self._coalescer, self._completer
        if co is None:
            return
        co.close(timeout)
        cm.close(timeout)
        co.fail_leftovers()
        with self._lock:
            victims = [f for f in self._outstanding if not f.done()]
        for f in victims:
            self._fail(f, RuntimeError("EmbeddingIndex is closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
