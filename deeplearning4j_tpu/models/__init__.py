"""Model zoo (reference: deeplearning4j-zoo)."""

from deeplearning4j_tpu.models.zoo import (
    AlexNet,
    FaceNetNN4Small2,
    GoogLeNet,
    InceptionResNetV1,
    LeNet,
    ResNet50,
    SimpleCNN,
    TextGenerationLSTM,
    TransformerLM,
    VGG16,
    VGG19,
    ZooModel,
    greedy_generate,
    sample_generate,
    zoo_models,
)

__all__ = [
    "AlexNet", "FaceNetNN4Small2", "GoogLeNet", "InceptionResNetV1", "LeNet",
    "ResNet50", "SimpleCNN", "TextGenerationLSTM", "TransformerLM", "VGG16", "VGG19",
    "ZooModel", "greedy_generate", "sample_generate", "zoo_models",
]
