"""Model zoo: instantiable standard architectures.

Reference: deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/ —
ZooModel.java:23 (abstract model), InstantiableModel.java:9, and the ten
models under zoo/model/. Architectures and hyperparameters follow the
reference files (cited per class); layouts are TPU-first (NHWC images,
[B,T,F] sequences) and every model compiles to a single XLA program through
MultiLayerNetwork / ComputationGraph.

Divergences from the reference, by design:
- ``init_pretrained`` raises: the reference downloads pretrained zips from
  blob.deeplearning4j.org (ZooModel.java:40-52); this environment has no
  egress. Weights can instead be restored from a local model zip.
- GoogLeNet's head uses global average pooling instead of the reference's
  fixed 7x7 average pool (GoogLeNet.java:114 assumes a 7x7 feature map that
  its own downsampling stack never produces — a known bug in that vintage).
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.conf.layers.core import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.attention import (
    PositionalEncodingLayer,
    SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.conf.layers.misc import CenterLossOutputLayer
from deeplearning4j_tpu.nn.conf.layers.normalization import (
    BatchNormalization,
    LayerNormalization,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.conf.layers.pooling import GlobalPoolingLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    GravesLSTM,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import (
    AdaDelta,
    Adam,
    Nesterovs,
    RmsProp,
)
from deeplearning4j_tpu.nn.weights import Distribution


class ZooModel:
    """Base for instantiable zoo models (reference: zoo/ZooModel.java:23,
    zoo/InstantiableModel.java:9).

    ``input_shape`` is (height, width, channels) — NHWC, unlike the
    reference's (channels, height, width).
    """

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Optional[tuple] = None, dtype: str = "float32",
                 compute_dtype: Optional[str] = None,
                 quantize: Optional[str] = None):
        self.num_labels = num_labels
        self.seed = seed
        self.dtype = dtype
        self.compute_dtype = compute_dtype
        #: "int8" quantizes the initialized net's dense/conv/attention
        #: weights in place at init() (optimize/quantize.py); None (the
        #: default) keeps full-precision params bit-exact
        self.quantize = quantize
        if input_shape is not None:
            self.input_shape = tuple(input_shape)

    def conf(self):
        raise NotImplementedError

    def init(self):
        c = self.conf()
        c.compute_dtype = self.compute_dtype
        net = (ComputationGraph(c)
               if type(c).__name__ == "ComputationGraphConfiguration"
               else MultiLayerNetwork(c))
        net = net.init()
        if self.quantize is not None:
            from deeplearning4j_tpu.optimize.quantize import quantize_net
            net = quantize_net(net, self.quantize)
        return net

    def init_pretrained(self, pretrained_type: str = "imagenet"):
        raise NotImplementedError(
            "Pretrained weights require network access (reference downloads "
            "from blob.deeplearning4j.org, ZooModel.java:40-52). Restore from "
            "a local zip via utils.model_serializer.load_model instead.")

    def model_type(self) -> str:
        return "MultiLayerNetwork"


class LeNet(ZooModel):
    """LeNet-5 for MNIST (reference: zoo/model/LeNet.java:31,79-108).
    conv5x5(20) -> max2 -> conv5x5(50) -> max2 -> dense500 -> softmax."""

    input_shape = (28, 28, 1)

    def __init__(self, num_labels: int = 10, **kw):
        super().__init__(num_labels=num_labels, **kw)

    def conf(self):
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).activation("identity").weight_init("xavier")
                .updater(AdaDelta()).dtype(self.dtype)
                .list(
                    ConvolutionLayer(name="cnn1", n_out=20, kernel_size=(5, 5),
                                     stride=(1, 1), convolution_mode="same",
                                     activation="relu"),
                    SubsamplingLayer(name="maxpool1", kernel_size=(2, 2),
                                     stride=(2, 2)),
                    ConvolutionLayer(name="cnn2", n_out=50, kernel_size=(5, 5),
                                     stride=(1, 1), convolution_mode="same",
                                     activation="relu"),
                    SubsamplingLayer(name="maxpool2", kernel_size=(2, 2),
                                     stride=(2, 2)),
                    DenseLayer(name="ffn1", n_out=500, activation="relu"),
                    OutputLayer(name="output", n_out=self.num_labels,
                                activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


class SimpleCNN(ZooModel):
    """Five conv/BN blocks + global-avg-pool head (reference:
    zoo/model/SimpleCNN.java:71-131)."""

    input_shape = (48, 48, 1)

    def __init__(self, num_labels: int = 10, **kw):
        super().__init__(num_labels=num_labels, **kw)

    def conf(self):
        h, w, c = self.input_shape

        def block(k, n, drop=True):
            layers = [
                ConvolutionLayer(n_out=n, kernel_size=(k, k),
                                 convolution_mode="same"),
                BatchNormalization(),
                ConvolutionLayer(n_out=n, kernel_size=(k, k),
                                 convolution_mode="same"),
                BatchNormalization(),
                ActivationLayer(activation="relu"),
                SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2),
                                 stride=(2, 2)),
            ]
            if drop:
                layers.append(DropoutLayer(dropout=0.5))
            return layers

        layers = (block(7, 16) + block(5, 32) + block(3, 64) + block(3, 128)
                  + [ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                      convolution_mode="same"),
                     BatchNormalization(),
                     ConvolutionLayer(n_out=self.num_labels,
                                      kernel_size=(3, 3),
                                      convolution_mode="same"),
                     GlobalPoolingLayer(pooling_type="avg"),
                     ActivationLayer(activation="softmax"),
                     # loss head over the softmaxed pooled logits
                     ])
        # The reference ends at the softmax ActivationLayer (SimpleCNN.java:
        # 124-126) and trains via an external loss; here we make the net
        # trainable standalone by using an OutputLayer head instead of the
        # last Activation+GlobalPooling pair.
        layers = layers[:-2] + [GlobalPoolingLayer(pooling_type="avg"),
                                OutputLayer(n_out=self.num_labels,
                                            activation="softmax",
                                            loss="mcxent")]
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).activation("identity").weight_init("relu")
                .updater(AdaDelta()).dtype(self.dtype)
                .list(*layers)
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


class AlexNet(ZooModel):
    """AlexNet, one-tower variant (reference: zoo/model/AlexNet.java:41,88-140).
    Keeps the reference's (quirky) strides so layer shapes match."""

    input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        non_zero_bias = 1.0
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).activation("relu")
                .weight_init("distribution")
                .dist(Distribution.normal(0.0, 0.01))
                .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
                .l2(5e-4).dtype(self.dtype)
                .list(
                    ConvolutionLayer(name="cnn1", n_out=64,
                                     kernel_size=(11, 11), stride=(4, 4),
                                     padding=(2, 2),
                                     convolution_mode="truncate"),
                    SubsamplingLayer(name="maxpool1", kernel_size=(3, 3),
                                     stride=(2, 2), padding=(1, 1),
                                     convolution_mode="truncate"),
                    ConvolutionLayer(name="cnn2", n_out=192,
                                     kernel_size=(5, 5), stride=(2, 2),
                                     padding=(2, 2),
                                     convolution_mode="truncate",
                                     bias_init=non_zero_bias),
                    SubsamplingLayer(name="maxpool2", kernel_size=(3, 3),
                                     stride=(2, 2)),
                    ConvolutionLayer(name="cnn3", n_out=384,
                                     kernel_size=(3, 3), stride=(1, 1),
                                     padding=(1, 1)),
                    ConvolutionLayer(name="cnn4", n_out=256,
                                     kernel_size=(3, 3), stride=(1, 1),
                                     padding=(1, 1), bias_init=non_zero_bias),
                    ConvolutionLayer(name="cnn5", n_out=256,
                                     kernel_size=(3, 3), stride=(1, 1),
                                     padding=(1, 1), bias_init=non_zero_bias),
                    SubsamplingLayer(name="maxpool3", kernel_size=(3, 3),
                                     stride=(7, 7)),
                    DenseLayer(name="ffn1", n_out=4096,
                               dist=Distribution.normal(0, 0.005),
                               weight_init="distribution",
                               bias_init=non_zero_bias, dropout=0.5),
                    DenseLayer(name="ffn2", n_out=4096,
                               dist=Distribution.normal(0, 0.005),
                               weight_init="distribution",
                               bias_init=non_zero_bias, dropout=0.5),
                    OutputLayer(name="output", n_out=self.num_labels,
                                activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


def _vgg_convs(*spec):
    """spec: sequence of channel counts; 'M' inserts a 2x2 max pool."""
    layers = []
    for s in spec:
        if s == "M":
            layers.append(SubsamplingLayer(pooling_type="max",
                                           kernel_size=(2, 2), stride=(2, 2)))
        else:
            layers.append(ConvolutionLayer(n_out=s, kernel_size=(3, 3),
                                           stride=(1, 1), padding=(1, 1)))
    return layers


class VGG16(ZooModel):
    """VGG-16 (reference: zoo/model/VGG16.java:35,91-160; conv-only head as in
    the reference, which comments out the 4096 dense layers)."""

    input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        convs = _vgg_convs(64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                           512, 512, 512, "M", 512, 512, 512, "M")
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).activation("relu")
                .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
                .dtype(self.dtype)
                .list(*convs,
                      OutputLayer(name="output", n_out=self.num_labels,
                                  activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


class VGG19(ZooModel):
    """VGG-19 (reference: zoo/model/VGG19.java)."""

    input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        convs = _vgg_convs(64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
                           512, 512, 512, 512, "M", 512, 512, 512, 512, "M")
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).activation("relu")
                .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
                .dtype(self.dtype)
                .list(*convs,
                      OutputLayer(name="output", n_out=self.num_labels,
                                  activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


class ResNet50(ZooModel):
    """ResNet-50 as a ComputationGraph (reference: zoo/model/ResNet50.java:
    33,82 graphBuilder, :91-125 identityBlock, :128-172 convBlock). The
    residual blocks are ElementWiseVertex(add) joins — on TPU the whole graph
    is one XLA program; BN+ReLU fuse into the convolutions.

    Note: the reference's fan-in-independent N(0, 0.5) weight init
    (ResNet50.java:178-179, reproduced below) makes the UNTRAINED network's
    eval-mode forward overflow float32 (~24x activation growth per conv
    through 50 layers; BN running stats are identity at init). This matches
    the reference; training is finite from step one because train-mode BN
    normalizes with batch statistics. Use ``weight_init("relu")`` on a
    custom build if you need sane eval-mode activations at init."""

    input_shape = (224, 224, 3)

    def _conv_bn_act(self, g, name, n_out, kernel, stride, mode, input_name,
                     act="relu"):
        g.add_layer(name, ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                                           stride=stride,
                                           convolution_mode=mode), input_name)
        g.add_layer(name + "_bn", BatchNormalization(), name)
        if act is None:
            return name + "_bn"
        g.add_layer(name + "_act", ActivationLayer(activation=act),
                    name + "_bn")
        return name + "_act"

    def _identity_block(self, g, kernel, filters, stage, block, input_name):
        n = f"res{stage}{block}"
        f1, f2, f3 = filters
        a = self._conv_bn_act(g, n + "_2a", f1, (1, 1), (1, 1), "truncate",
                              input_name)
        b = self._conv_bn_act(g, n + "_2b", f2, kernel, (1, 1), "same", a)
        c = self._conv_bn_act(g, n + "_2c", f3, (1, 1), (1, 1), "truncate", b,
                              act=None)
        g.add_vertex(n + "_add", ElementWiseVertex(op="add"), c, input_name)
        g.add_layer(n, ActivationLayer(activation="relu"), n + "_add")
        return n

    def _conv_block(self, g, kernel, filters, stage, block, stride,
                    input_name):
        n = f"res{stage}{block}"
        f1, f2, f3 = filters
        a = self._conv_bn_act(g, n + "_2a", f1, (1, 1), stride, "truncate",
                              input_name)
        b = self._conv_bn_act(g, n + "_2b", f2, kernel, (1, 1), "same", a)
        c = self._conv_bn_act(g, n + "_2c", f3, (1, 1), (1, 1), "truncate", b,
                              act=None)
        s = self._conv_bn_act(g, n + "_1", f3, (1, 1), stride, "truncate",
                              input_name, act=None)
        g.add_vertex(n + "_add", ElementWiseVertex(op="add"), c, s)
        g.add_layer(n, ActivationLayer(activation="relu"), n + "_add")
        return n

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).activation("identity")
             .updater(RmsProp(learning_rate=0.1, rms_decay=0.96, epsilon=0.001))
             .weight_init("distribution").dist(Distribution.normal(0.0, 0.5))
             .l1(1e-7).l2(5e-5).dtype(self.dtype)
             .graph_builder()
             .add_inputs("input"))
        g.add_layer("stem_zero", ZeroPaddingLayer(pad_top=3, pad_bottom=3,
                                                  pad_left=3, pad_right=3),
                    "input")
        stem = self._conv_bn_act(g, "stem_cnn1", 64, (7, 7), (2, 2),
                                 "truncate", "stem_zero")
        g.add_layer("stem_maxpool1",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2)), stem)

        x = self._conv_block(g, (3, 3), (64, 64, 256), 2, "a", (2, 2),
                             "stem_maxpool1")
        x = self._identity_block(g, (3, 3), (64, 64, 256), 2, "b", x)
        x = self._identity_block(g, (3, 3), (64, 64, 256), 2, "c", x)

        x = self._conv_block(g, (3, 3), (128, 128, 512), 3, "a", (2, 2), x)
        for blk in "bcd":
            x = self._identity_block(g, (3, 3), (128, 128, 512), 3, blk, x)

        x = self._conv_block(g, (3, 3), (256, 256, 1024), 4, "a", (2, 2), x)
        for blk in "bcdef":
            x = self._identity_block(g, (3, 3), (256, 256, 1024), 4, blk, x)

        x = self._conv_block(g, (3, 3), (512, 512, 2048), 5, "a", (2, 2), x)
        x = self._identity_block(g, (3, 3), (512, 512, 2048), 5, "b", x)
        x = self._identity_block(g, (3, 3), (512, 512, 2048), 5, "c", x)

        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("output", OutputLayer(n_out=self.num_labels,
                                          activation="softmax", loss="mcxent"),
                    "avgpool")
        g.set_outputs("output")
        g.set_input_types(InputType.convolutional(h, w, c))
        return g.build()

    def model_type(self) -> str:
        return "ComputationGraph"


class GoogLeNet(ZooModel):
    """GoogLeNet / Inception-v1 as a ComputationGraph (reference:
    zoo/model/GoogLeNet.java:84-96 inception, :99-175 conf)."""

    input_shape = (224, 224, 3)

    def _inception(self, g, name, config, input_name):
        (c1,), (c3r, c3), (c5r, c5), (pp,) = config
        g.add_layer(f"{name}-cnn1",
                    ConvolutionLayer(n_out=c1, kernel_size=(1, 1),
                                     bias_init=0.2, activation="relu"),
                    input_name)
        g.add_layer(f"{name}-cnn2",
                    ConvolutionLayer(n_out=c3r, kernel_size=(1, 1),
                                     bias_init=0.2, activation="relu"),
                    input_name)
        g.add_layer(f"{name}-cnn3",
                    ConvolutionLayer(n_out=c5r, kernel_size=(1, 1),
                                     bias_init=0.2, activation="relu"),
                    input_name)
        g.add_layer(f"{name}-max1",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(1, 1), padding=(1, 1)),
                    input_name)
        g.add_layer(f"{name}-cnn4",
                    ConvolutionLayer(n_out=c3, kernel_size=(3, 3),
                                     padding=(1, 1), bias_init=0.2,
                                     activation="relu"), f"{name}-cnn2")
        g.add_layer(f"{name}-cnn5",
                    ConvolutionLayer(n_out=c5, kernel_size=(5, 5),
                                     padding=(2, 2), bias_init=0.2,
                                     activation="relu"), f"{name}-cnn3")
        g.add_layer(f"{name}-cnn6",
                    ConvolutionLayer(n_out=pp, kernel_size=(1, 1),
                                     bias_init=0.2, activation="relu"),
                    f"{name}-max1")
        g.add_vertex(f"{name}-depthconcat1", MergeVertex(), f"{name}-cnn1",
                     f"{name}-cnn4", f"{name}-cnn5", f"{name}-cnn6")
        return f"{name}-depthconcat1"

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).activation("relu").weight_init("xavier")
             .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
             .l2(2e-4).dtype(self.dtype)
             .graph_builder()
             .add_inputs("input"))
        g.add_layer("cnn1", ConvolutionLayer(n_out=64, kernel_size=(7, 7),
                                             stride=(2, 2), padding=(3, 3),
                                             bias_init=0.2), "input")
        g.add_layer("max1", SubsamplingLayer(pooling_type="max",
                                             kernel_size=(3, 3),
                                             stride=(2, 2), padding=(1, 1)),
                    "cnn1")
        g.add_layer("lrn1", LocalResponseNormalization(n=5, alpha=1e-4,
                                                       beta=0.75), "max1")
        g.add_layer("cnn2", ConvolutionLayer(n_out=64, kernel_size=(1, 1),
                                             bias_init=0.2), "lrn1")
        g.add_layer("cnn3", ConvolutionLayer(n_out=192, kernel_size=(3, 3),
                                             padding=(1, 1), bias_init=0.2),
                    "cnn2")
        g.add_layer("lrn2", LocalResponseNormalization(n=5, alpha=1e-4,
                                                       beta=0.75), "cnn3")
        g.add_layer("max2", SubsamplingLayer(pooling_type="max",
                                             kernel_size=(3, 3),
                                             stride=(2, 2), padding=(1, 1)),
                    "lrn2")
        x = self._inception(g, "3a", ((64,), (96, 128), (16, 32), (32,)),
                            "max2")
        x = self._inception(g, "3b", ((128,), (128, 192), (32, 96), (64,)), x)
        g.add_layer("max3", SubsamplingLayer(pooling_type="max",
                                             kernel_size=(3, 3),
                                             stride=(2, 2), padding=(1, 1)),
                    x)
        x = self._inception(g, "4a", ((192,), (96, 208), (16, 48), (64,)),
                            "max3")
        x = self._inception(g, "4b", ((160,), (112, 224), (24, 64), (64,)), x)
        x = self._inception(g, "4c", ((128,), (128, 256), (24, 64), (64,)), x)
        x = self._inception(g, "4d", ((112,), (144, 288), (32, 64), (64,)), x)
        x = self._inception(g, "4e", ((256,), (160, 320), (32, 128), (128,)),
                            x)
        g.add_layer("max4", SubsamplingLayer(pooling_type="max",
                                             kernel_size=(3, 3),
                                             stride=(2, 2), padding=(1, 1)),
                    x)
        x = self._inception(g, "5a", ((256,), (160, 320), (32, 128), (128,)),
                            "max4")
        x = self._inception(g, "5b", ((384,), (192, 384), (48, 128), (128,)),
                            x)
        g.add_layer("avg3", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("fc1", DenseLayer(n_out=1024, dropout=0.4), "avg3")
        g.add_layer("output", OutputLayer(n_out=self.num_labels,
                                          activation="softmax", loss="mcxent"),
                    "fc1")
        g.set_outputs("output")
        g.set_input_types(InputType.convolutional(h, w, c))
        return g.build()

    def model_type(self) -> str:
        return "ComputationGraph"


class FaceNetNN4Small2(ZooModel):
    """FaceNet NN4.small2 embedding net with center-loss head (reference:
    zoo/model/FaceNetNN4Small2.java:80-340 — stem, inception-2..5 blocks,
    avg-pool, bottleneck dense, CenterLossOutputLayer). Inception internals
    follow zoo/model/helper/FaceNetHelper.appendGraph."""

    input_shape = (96, 96, 3)
    embedding_size = 128

    def __init__(self, num_labels: int = 1000, **kw):
        super().__init__(num_labels=num_labels, **kw)

    def _conv_bn(self, g, name, n_out, kernel, stride, pad, input_name):
        g.add_layer(name, ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                                           stride=stride, padding=pad),
                    input_name)
        g.add_layer(name + "_bn", BatchNormalization(), name)
        g.add_layer(name + "_act", ActivationLayer(activation="relu"),
                    name + "_bn")
        return name + "_act"

    def _inception(self, g, name, reduce_sizes, out_sizes, input_name):
        """4 branches: 1x1, 1x1->3x3, 1x1->5x5, pool->1x1 (FaceNetHelper);
        reduce_sizes = (3x3-reduce, 5x5-reduce, pool-proj, 1x1)."""
        r3, r5, p1, c1 = reduce_sizes
        c3, c5 = out_sizes
        branches = []
        if c1:
            branches.append(self._conv_bn(g, f"{name}-1x1", c1, (1, 1),
                                          (1, 1), (0, 0), input_name))
        a = self._conv_bn(g, f"{name}-3x3r", r3, (1, 1), (1, 1), (0, 0),
                          input_name)
        branches.append(self._conv_bn(g, f"{name}-3x3", c3, (3, 3), (1, 1),
                                      (1, 1), a))
        if r5 and c5:  # reference 5a block omits the 5x5 branch
            b = self._conv_bn(g, f"{name}-5x5r", r5, (1, 1), (1, 1), (0, 0),
                              input_name)
            branches.append(self._conv_bn(g, f"{name}-5x5", c5, (5, 5),
                                          (1, 1), (2, 2), b))
        g.add_layer(f"{name}-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(1, 1), padding=(1, 1)),
                    input_name)
        branches.append(self._conv_bn(g, f"{name}-poolproj", p1, (1, 1),
                                      (1, 1), (0, 0), f"{name}-pool"))
        g.add_vertex(f"{name}-merge", MergeVertex(), *branches)
        return f"{name}-merge"

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).activation("relu").weight_init("relu")
             .updater(Nesterovs(learning_rate=1e-3, momentum=0.9))
             .dtype(self.dtype)
             .graph_builder()
             .add_inputs("input"))
        x = self._conv_bn(g, "stem-cnn1", 64, (7, 7), (2, 2), (3, 3), "input")
        g.add_layer("stem-pool1",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2), padding=(1, 1)), x)
        x = self._conv_bn(g, "inception-2-cnn1", 64, (1, 1), (1, 1), (0, 0),
                          "stem-pool1")
        x = self._conv_bn(g, "inception-2-cnn2", 192, (3, 3), (1, 1), (1, 1),
                          x)
        g.add_layer("inception-2-pool1",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2), padding=(1, 1)), x)
        x = self._inception(g, "3a", (96, 16, 32, 64), (128, 32),
                            "inception-2-pool1")
        x = self._inception(g, "3b", (96, 32, 64, 64), (128, 64), x)
        g.add_layer("3c-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2), padding=(1, 1)), x)
        x = self._inception(g, "4a", (96, 32, 128, 256), (192, 64),
                            "3c-pool")
        g.add_layer("4e-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2), padding=(1, 1)), x)
        x = self._inception(g, "5a", (96, 0, 96, 256), (384, 0), "4e-pool")
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "avgpool")
        g.add_layer("lossLayer",
                    CenterLossOutputLayer(n_out=self.num_labels,
                                          activation="softmax", loss="mcxent",
                                          alpha=0.1, lambda_=3e-4),
                    "bottleneck")
        g.set_outputs("lossLayer")
        g.set_input_types(InputType.convolutional(h, w, c))
        return g.build()

    def model_type(self) -> str:
        return "ComputationGraph"


class InceptionResNetV1(ZooModel):
    """Inception-ResNet v1 embedding net (reference:
    zoo/model/InceptionResNetV1.java:60-322 — stem, 5x block35, reduction-A,
    10x block17, reduction-B, 5x block8, avgpool, bottleneck, center-loss).
    Block counts follow the reference; residual joins are
    ElementWiseVertex(add) with a post-add activation."""

    input_shape = (160, 160, 3)
    embedding_size = 128

    def __init__(self, num_labels: int = 1000, **kw):
        super().__init__(num_labels=num_labels, **kw)

    def _conv_bn(self, g, name, n_out, kernel, stride, pad, input_name,
                 act="relu"):
        g.add_layer(name, ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                                           stride=stride, padding=pad),
                    input_name)
        g.add_layer(name + "_bn", BatchNormalization(), name)
        if act is None:
            return name + "_bn"
        g.add_layer(name + "_act", ActivationLayer(activation=act),
                    name + "_bn")
        return name + "_act"

    def _block35(self, g, name, input_name, ch=256):
        b1 = self._conv_bn(g, f"{name}-b1", 32, (1, 1), (1, 1), (0, 0),
                           input_name)
        b2 = self._conv_bn(g, f"{name}-b2a", 32, (1, 1), (1, 1), (0, 0),
                           input_name)
        b2 = self._conv_bn(g, f"{name}-b2b", 32, (3, 3), (1, 1), (1, 1), b2)
        b3 = self._conv_bn(g, f"{name}-b3a", 32, (1, 1), (1, 1), (0, 0),
                           input_name)
        b3 = self._conv_bn(g, f"{name}-b3b", 32, (3, 3), (1, 1), (1, 1), b3)
        b3 = self._conv_bn(g, f"{name}-b3c", 32, (3, 3), (1, 1), (1, 1), b3)
        g.add_vertex(f"{name}-merge", MergeVertex(), b1, b2, b3)
        up = self._conv_bn(g, f"{name}-up", ch, (1, 1), (1, 1), (0, 0),
                           f"{name}-merge", act=None)
        g.add_vertex(f"{name}-add", ElementWiseVertex(op="add"), input_name,
                     up)
        g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                    f"{name}-add")
        return f"{name}"

    def _block17(self, g, name, input_name, ch=896):
        b1 = self._conv_bn(g, f"{name}-b1", 128, (1, 1), (1, 1), (0, 0),
                           input_name)
        b2 = self._conv_bn(g, f"{name}-b2a", 128, (1, 1), (1, 1), (0, 0),
                           input_name)
        b2 = self._conv_bn(g, f"{name}-b2b", 128, (1, 7), (1, 1), (0, 3), b2)
        b2 = self._conv_bn(g, f"{name}-b2c", 128, (7, 1), (1, 1), (3, 0), b2)
        g.add_vertex(f"{name}-merge", MergeVertex(), b1, b2)
        up = self._conv_bn(g, f"{name}-up", ch, (1, 1), (1, 1), (0, 0),
                           f"{name}-merge", act=None)
        g.add_vertex(f"{name}-add", ElementWiseVertex(op="add"), input_name,
                     up)
        g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                    f"{name}-add")
        return f"{name}"

    def _block8(self, g, name, input_name, ch=1792):
        b1 = self._conv_bn(g, f"{name}-b1", 192, (1, 1), (1, 1), (0, 0),
                           input_name)
        b2 = self._conv_bn(g, f"{name}-b2a", 192, (1, 1), (1, 1), (0, 0),
                           input_name)
        b2 = self._conv_bn(g, f"{name}-b2b", 192, (1, 3), (1, 1), (0, 1), b2)
        b2 = self._conv_bn(g, f"{name}-b2c", 192, (3, 1), (1, 1), (1, 0), b2)
        g.add_vertex(f"{name}-merge", MergeVertex(), b1, b2)
        up = self._conv_bn(g, f"{name}-up", ch, (1, 1), (1, 1), (0, 0),
                           f"{name}-merge", act=None)
        g.add_vertex(f"{name}-add", ElementWiseVertex(op="add"), input_name,
                     up)
        g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                    f"{name}-add")
        return f"{name}"

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).activation("relu").weight_init("relu")
             .updater(RmsProp(learning_rate=0.1, rms_decay=0.96, epsilon=0.001))
             .dtype(self.dtype)
             .graph_builder()
             .add_inputs("input"))
        # stem (InceptionResNetV1.java stem: 3x conv, maxpool, 3x conv)
        x = self._conv_bn(g, "stem1", 32, (3, 3), (2, 2), (0, 0), "input")
        x = self._conv_bn(g, "stem2", 32, (3, 3), (1, 1), (0, 0), x)
        x = self._conv_bn(g, "stem3", 64, (3, 3), (1, 1), (1, 1), x)
        g.add_layer("stem-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2)), x)
        x = self._conv_bn(g, "stem4", 80, (1, 1), (1, 1), (0, 0), "stem-pool")
        x = self._conv_bn(g, "stem5", 192, (3, 3), (1, 1), (0, 0), x)
        x = self._conv_bn(g, "stem6", 256, (3, 3), (2, 2), (0, 0), x)
        for i in range(5):
            x = self._block35(g, f"block35-{i}", x)
        # reduction-A
        ra1 = self._conv_bn(g, "redA-b1", 384, (3, 3), (2, 2), (0, 0), x)
        ra2 = self._conv_bn(g, "redA-b2a", 192, (1, 1), (1, 1), (0, 0), x)
        ra2 = self._conv_bn(g, "redA-b2b", 192, (3, 3), (1, 1), (1, 1), ra2)
        ra2 = self._conv_bn(g, "redA-b2c", 256, (3, 3), (2, 2), (0, 0), ra2)
        g.add_layer("redA-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2)), x)
        g.add_vertex("redA", MergeVertex(), ra1, ra2, "redA-pool")
        x = "redA"
        for i in range(10):
            x = self._block17(g, f"block17-{i}", x)
        # reduction-B
        rb1 = self._conv_bn(g, "redB-b1a", 256, (1, 1), (1, 1), (0, 0), x)
        rb1 = self._conv_bn(g, "redB-b1b", 384, (3, 3), (2, 2), (0, 0), rb1)
        rb2 = self._conv_bn(g, "redB-b2a", 256, (1, 1), (1, 1), (0, 0), x)
        rb2 = self._conv_bn(g, "redB-b2b", 256, (3, 3), (2, 2), (0, 0), rb2)
        rb3 = self._conv_bn(g, "redB-b3a", 256, (1, 1), (1, 1), (0, 0), x)
        rb3 = self._conv_bn(g, "redB-b3b", 256, (3, 3), (1, 1), (1, 1), rb3)
        rb3 = self._conv_bn(g, "redB-b3c", 256, (3, 3), (2, 2), (0, 0), rb3)
        g.add_layer("redB-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                     stride=(2, 2)), x)
        g.add_vertex("redB", MergeVertex(), rb1, rb2, rb3, "redB-pool")
        x = "redB"
        for i in range(5):
            x = self._block8(g, f"block8-{i}", x)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"),
                    "avgpool")
        g.add_layer("lossLayer",
                    CenterLossOutputLayer(n_out=self.num_labels,
                                          activation="softmax", loss="mcxent",
                                          alpha=0.1, lambda_=3e-4),
                    "bottleneck")
        g.set_outputs("lossLayer")
        g.set_input_types(InputType.convolutional(h, w, c))
        return g.build()

    def model_type(self) -> str:
        return "ComputationGraph"


class TextGenerationLSTM(ZooModel):
    """Char-level text-generation LSTM (reference:
    zoo/model/TextGenerationLSTM.java:77-94): GravesLSTM(256) x2 +
    RnnOutputLayer, truncated BPTT 50/50. On TPU the LSTM is a lax.scan whose
    per-step gate matmul hits the MXU."""

    def __init__(self, num_labels: int = 77, max_length: int = 40, **kw):
        super().__init__(num_labels=num_labels, **kw)
        self.max_length = max_length
        self.input_shape = (max_length, num_labels)

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).weight_init("xavier")
                .updater(RmsProp(learning_rate=0.01)).l2(0.001)
                .dtype(self.dtype)
                .list(
                    GravesLSTM(n_out=256, activation="tanh"),
                    GravesLSTM(n_out=256, activation="tanh"),
                    RnnOutputLayer(n_out=self.num_labels,
                                   activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(self.num_labels))
                .t_bptt_lengths(50, 50)
                .build())


class TransformerLM(ZooModel):
    """Causal transformer language model (beyond reference parity — the
    2017-era zoo's sequence model is TextGenerationLSTM; this is its
    modern sibling, built from the same framework pieces so the flash
    attention path has a model-level consumer).

    Pre-norm residual blocks as a ComputationGraph: one-hot tokens ->
    Dense embed + sinusoidal positions -> n_blocks x [LN -> causal
    multi-head SelfAttention (helper='auto': Pallas flash kernel when
    supported) -> +residual -> LN -> Dense(4D, gelu) -> Dense(D) ->
    +residual] -> LN -> RnnOutputLayer softmax/mcxent per timestep.
    """

    def __init__(self, num_labels: int = 256, max_length: int = 128,
                 d_model: int = 256, n_heads: int = 8, n_blocks: int = 4,
                 remat: bool = False, **kw):
        super().__init__(num_labels=num_labels, **kw)
        self.max_length = max_length
        self.d_model = d_model
        self.n_heads = n_heads
        self.n_blocks = n_blocks
        # jax.checkpoint the attention / FFN-expansion vertices: backward
        # recomputes their internal activations at the cost of one extra
        # forward. Per-vertex boundaries mean boundary outputs are still
        # stored as residuals (see LayerVertex.remat) — the saving is the
        # inside-vertex intermediates, not whole-block memory.
        self.remat = remat
        self.input_shape = (max_length, num_labels)

    def conf(self):
        D = self.d_model
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).weight_init("xavier")
             .updater(Adam(learning_rate=3e-4))
             .dtype(self.dtype)
             .graph_builder()
             .add_inputs("tokens")
             .set_input_types(InputType.recurrent(self.num_labels,
                                                  self.max_length)))
        g.add_layer("embed", DenseLayer(n_out=D, activation="identity"),
                    "tokens")
        g.add_layer("pos", PositionalEncodingLayer(), "embed")
        x = "pos"
        for i in range(self.n_blocks):
            g.add_layer(f"ln{i}a", LayerNormalization(), x)
            g.add_layer(f"attn{i}",
                        SelfAttentionLayer(n_out=D, n_heads=self.n_heads,
                                           causal=True, helper="auto"),
                        f"ln{i}a", remat=self.remat)
            g.add_vertex(f"res{i}a", ElementWiseVertex(op="add"),
                         x, f"attn{i}")
            g.add_layer(f"ln{i}b", LayerNormalization(), f"res{i}a")
            g.add_layer(f"ff{i}a", DenseLayer(n_out=4 * D,
                                              activation="gelu"),
                        f"ln{i}b", remat=self.remat)
            g.add_layer(f"ff{i}b", DenseLayer(n_out=D,
                                              activation="identity"),
                        f"ff{i}a")
            g.add_vertex(f"res{i}b", ElementWiseVertex(op="add"),
                         f"res{i}a", f"ff{i}b")
            x = f"res{i}b"
        g.add_layer("ln_f", LayerNormalization(), x)
        g.add_layer("output",
                    RnnOutputLayer(n_out=self.num_labels,
                                   activation="softmax", loss="mcxent"),
                    "ln_f")
        g.set_outputs("output")
        return g.build()

    def model_type(self) -> str:
        return "ComputationGraph"


def lm_stream_forward(net):
    """One streaming forward chunk through ``net`` as a pure function:
    ``fwd(params, state, x, carry, mask=None) -> (out, new_carry)``.

    Papering over the MultiLayerNetwork/ComputationGraph `_forward`
    signature split in ONE place so every decode program family —
    `_device_generate`'s fused scan, GenerationServer's prefill-into-slot
    and pooled decode step — traces the same forward."""
    is_graph = hasattr(net.conf, "network_inputs")

    def fwd(params, state, x, carry, mask=None):
        if is_graph:
            outs, _, new_carry, _, _ = net._forward(
                params, state, [x], [mask], train=False, rng=None,
                carry=carry)
            return outs[0], new_carry
        out, _, new_carry, _ = net._forward(params, state, x, mask,
                                            train=False, rng=None,
                                            carry=carry)
        return out, new_carry

    return fwd


def sampled_next_token(probs, keys, temperature, top_k):
    """Next-token select with TRACED per-row sampling params.

    probs: [B, V] softmax outputs; keys: [B, 2] uint32 PRNG keys;
    temperature/top_k: [B] float/int arrays — traced VALUES, not static
    args, so a batch mixing greedy and sampled requests (any temp/top_k
    combination) shares one compiled program. Rows with temperature <= 0
    take the argmax — the same op `_device_generate` compiles for its
    greedy path, so greedy results are bit-identical between the two.
    """
    import jax
    import jax.numpy as jnp

    V = probs.shape[-1]
    greedy = jnp.argmax(probs, axis=-1)
    logits = jnp.log(jnp.maximum(probs, 1e-30)) \
        / jnp.maximum(temperature, 1e-30)[:, None]
    # per-row k-th-largest threshold via one full sort; top_k <= 0 rows
    # disable the cut (threshold at the row minimum)
    srt = jnp.sort(logits, axis=-1)                      # ascending
    k_idx = jnp.clip(V - top_k, 0, V - 1)
    kth = jnp.take_along_axis(srt, k_idx[:, None], axis=-1)
    cut = (top_k[:, None] > 0) & (logits < kth)
    logits = jnp.where(cut, -1e30, logits)
    sampled = jax.vmap(jax.random.categorical)(keys, logits)
    return jnp.where(temperature <= 0, greedy, sampled)


def spec_verify_tokens(probs, base_keys, counts, temperature, top_k):
    """Target-model token selection at K consecutive positions per row —
    the verification half of speculative decoding.

    probs: [B, K, V] softmax outputs of one chunked forward over
    [last_token, draft_1, ..., draft_{K-1}]; base_keys: [B, 2] uint32;
    counts: [B] index of the FIRST token being selected; temperature /
    top_k: [B] traced per-row values. Position i of row b selects with
    ``fold_in(base_keys[b], counts[b] + i)`` — the SAME key schedule the
    serial decode uses for that token index, which is what makes
    speculative acceptance bit-exact: every emitted token is literally
    the target model's selection under the serial schedule, regardless
    of what the draft proposed."""
    import jax
    import jax.numpy as jnp

    B, K, V = probs.shape
    idx = counts[:, None] + jnp.arange(K, dtype=counts.dtype)   # [B, K]
    keys = jax.vmap(jax.vmap(jax.random.fold_in, (None, 0)),
                    (0, 0))(base_keys, idx)                     # [B, K, 2]
    flat = sampled_next_token(probs.reshape(B * K, V),
                              keys.reshape(B * K, 2),
                              jnp.repeat(temperature, K),
                              jnp.repeat(top_k, K))
    return flat.reshape(B, K)


def greedy_generate(net, prompt_ids, steps: int, vocab: int,
                    device_loop: bool = True):
    """Greedy decoding — ``sample_generate`` with temperature 0 (see
    there for the KV-cache / device-loop mechanics)."""
    return sample_generate(net, prompt_ids, steps, vocab,
                           temperature=0.0, device_loop=device_loop)


def sample_generate(net, prompt_ids, steps: int, vocab: int,
                    temperature: float = 1.0, top_k: int = 0,
                    seed: int = 0, device_loop: bool = True):
    """Autoregressive decoding via KV-cache streaming: the prompt is
    consumed once, then each new token costs ONE incremental attention
    row (cached keys/values — O(T) per token) instead of a full O(T^2)
    re-forward. Works with any one-hot-input causal LM (TransformerLM;
    TextGenerationLSTM streams through its h/c the same way).

    ``temperature``: 0 = greedy argmax; otherwise tokens are sampled
    from softmax probabilities sharpened by 1/temperature (the
    char-modelling example's sampleFromDistribution semantics).
    ``top_k``: when > 0, restrict sampling to the k most likely tokens.

    ``device_loop=True`` (default) compiles the WHOLE decode as one XLA
    program — a ``lax.scan`` whose body is forward + next-token select +
    one-hot feedback (sampling uses jax.random.categorical with a
    per-step folded key) — so the host pays a single dispatch instead of
    one round-trip per token (measured ~115 ms/token of pure tunnel
    latency on the CI chip). ``device_loop=False`` streams through
    ``rnn_time_step`` one token at a time (same math, host-driven;
    sampling then uses numpy's RNG, so the two paths agree exactly only
    at temperature 0).

    prompt_ids: [B, T0] int array. Returns [B, steps] generated ids.
    """
    import numpy as np_

    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0 or top_k > vocab:
        raise ValueError(f"top_k must be in [0, vocab], got {top_k}")
    prompt_ids = np_.asarray(prompt_ids)
    if device_loop:
        return np_.asarray(_device_generate(net, prompt_ids, steps, vocab,
                                            temperature, top_k, seed))

    rs = np_.random.RandomState(seed)

    def pick(probs):  # [B, V] -> [B]
        if temperature <= 0:
            return probs.argmax(-1)
        logp = np_.log(np_.maximum(probs, 1e-30)) / temperature
        if top_k > 0:
            kth = np_.sort(logp, axis=-1)[:, -top_k][:, None]
            logp = np_.where(logp >= kth, logp, -1e30)
        p = np_.exp(logp - logp.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np_.stack([rs.choice(vocab, p=row) for row in p])

    eye = np_.eye(vocab, dtype=np_.float32)
    net.rnn_clear_previous_state()
    out = net.rnn_time_step(eye[prompt_ids])          # [B, T0, V]
    last = pick(np_.asarray(out)[:, -1])              # [B]
    generated = [last]
    for _ in range(steps - 1):
        out = net.rnn_time_step(eye[last][:, None, :])  # [B, 1, V]
        last = pick(np_.asarray(out)[:, 0])
        generated.append(last)
    return np_.stack(generated, axis=1)


def _device_generate(net, prompt_ids, steps: int, vocab: int,
                     temperature: float, top_k: int, seed: int):
    """One jitted program: consume the prompt, then lax.scan the
    token-by-token decode on device (KV caches ride in the scan carry)."""
    import jax
    import jax.numpy as jnp

    B = prompt_ids.shape[0]
    # generation is its own stream: any live rnn_time_step stream is
    # CLEARED (seeding below resets the overflow accounting, so leaving
    # the old carry in place would let a continued stream bypass the
    # guard and silently clamp-corrupt its cache)
    net.rnn_clear_previous_state()
    carry0 = net._seed_streaming_carry(B)
    cap = net._stream_capacity
    needed = prompt_ids.shape[1] + steps - 1
    if cap is not None and needed > cap:
        raise ValueError(
            f"KV cache overflow: prompt + generated positions ({needed}) "
            f"> max_cache ({cap}); raise SelfAttentionLayer.max_cache")

    # one compiled program per (shapes, steps, sampling config): cached
    # on the net like rnn_time_step's step fn — a serving loop must not
    # re-trace the whole scan program per request
    # at temperature 0 the traced pick() is a pure argmax that ignores
    # top_k: normalize it out of the key so greedy programs are not
    # recompiled once per distinct (ignored) top_k value
    key = ("generate", B, prompt_ids.shape[1], steps, vocab,
           float(temperature), int(top_k) if temperature > 0 else 0)
    if key not in net._output_cache:
        fwd = lm_stream_forward(net)

        def pick(probs, k):  # [B, V], key -> [B]
            if temperature <= 0:
                return jnp.argmax(probs, axis=-1)
            logits = jnp.log(jnp.maximum(probs, 1e-30)) / temperature
            if top_k > 0:
                kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
                logits = jnp.where(logits >= kth, logits, -1e30)
            return jax.random.categorical(k, logits)

        def generate(params, state, prompt_onehot, carry, rng):
            out, carry = fwd(params, state, prompt_onehot, carry)
            last = pick(out[:, -1], jax.random.fold_in(rng, 0))
            if steps == 1:
                return last[:, None]

            def body(c, i):
                carry, last = c
                x = jax.nn.one_hot(last, vocab,
                                   dtype=prompt_onehot.dtype)[:, None, :]
                o, carry = fwd(params, state, x, carry)
                nxt = pick(o[:, 0], jax.random.fold_in(rng, i))
                return (carry, nxt), nxt

            (_, _), rest = jax.lax.scan(body, (carry, last),
                                        jnp.arange(1, steps))
            return jnp.concatenate([last[:, None],
                                    jnp.moveaxis(rest, 0, 1)], axis=1)

        net._output_cache[key] = jax.jit(generate)

    eye = jnp.eye(vocab, dtype=jnp.dtype(net.conf.dtype))
    out = net._output_cache[key](net.params, net.state, eye[prompt_ids],
                                 carry0, jax.random.PRNGKey(seed))
    # the generation stream's carry lived only inside the program;
    # leave the net with no half-open stream
    net.rnn_clear_previous_state()
    return out


def zoo_models() -> dict:
    """Name -> ZooModel class registry (reference: zoo/ModelSelector.java;
    ``transformerlm`` is beyond-parity)."""
    return {
        "alexnet": AlexNet,
        "facenetnn4small2": FaceNetNN4Small2,
        "googlenet": GoogLeNet,
        "inceptionresnetv1": InceptionResNetV1,
        "lenet": LeNet,
        "resnet50": ResNet50,
        "simplecnn": SimpleCNN,
        "textgenlstm": TextGenerationLSTM,
        "transformerlm": TransformerLM,
        "vgg16": VGG16,
        "vgg19": VGG19,
    }
