"""ROC / AUC evaluation (reference: eval/ROC.java:53, ROCBinary, ROCMultiClass).

The reference evaluates at ``thresholdSteps`` fixed thresholds; we keep that exact
mode (threshold_steps > 0) and also support exact AUC (threshold_steps=0, using all
unique scores) which the reference added later.
"""

from __future__ import annotations

import numpy as np


class ROC:
    """Binary ROC: labels [B] or [B,1] or one-hot [B,2]; probs same shape."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self.scores: list = []
        self.targets: list = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, float)
        predictions = np.asarray(predictions, float)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        labels = labels.reshape(-1)
        predictions = predictions.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).astype(bool).reshape(-1)
            labels, predictions = labels[m], predictions[m]
        self.targets.append(labels)
        self.scores.append(predictions)
        return self

    def merge(self, other: "ROC"):
        self.targets.extend(other.targets)
        self.scores.extend(other.scores)
        return self

    def _collect(self):
        return np.concatenate(self.targets), np.concatenate(self.scores)

    def roc_curve(self):
        """Returns (fpr, tpr, thresholds)."""
        t, s = self._collect()
        if self.threshold_steps > 0:
            thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
        else:
            thresholds = np.concatenate([[np.inf], np.sort(np.unique(s))[::-1]])
        pos = t.sum()
        neg = len(t) - pos
        tpr = np.array([(s >= th).astype(float)[t > 0.5].sum() / max(pos, 1)
                        for th in thresholds])
        fpr = np.array([(s >= th).astype(float)[t <= 0.5].sum() / max(neg, 1)
                        for th in thresholds])
        order = np.argsort(fpr, kind="stable")
        return fpr[order], tpr[order], thresholds[order]

    def calculate_auc(self) -> float:
        fpr, tpr, _ = self.roc_curve()
        return float(np.trapezoid(tpr, fpr))

    def precision_recall_curve(self):
        t, s = self._collect()
        thresholds = np.sort(np.unique(s))[::-1]
        prec, rec = [], []
        pos = max(t.sum(), 1)
        for th in thresholds:
            pred = s >= th
            tp = (pred & (t > 0.5)).sum()
            prec.append(tp / max(pred.sum(), 1))
            rec.append(tp / pos)
        return np.array(rec), np.array(prec), thresholds

    def calculate_auprc(self) -> float:
        rec, prec, _ = self.precision_recall_curve()
        order = np.argsort(rec, kind="stable")
        return float(np.trapezoid(prec[order], rec[order]))

    def get_roc_curve(self):
        """Serializable curve object (reference: ROC.getRocCurve ->
        eval/curves/RocCurve.java with toJson round-trip)."""
        from deeplearning4j_tpu.evaluation.curves import RocCurve
        fpr, tpr, th = self.roc_curve()
        return RocCurve(thresholds=[float(x) for x in th],
                        fpr=[float(x) for x in fpr],
                        tpr=[float(x) for x in tpr])

    def get_precision_recall_curve(self):
        """Serializable curve (reference: ROC.getPrecisionRecallCurve)."""
        from deeplearning4j_tpu.evaluation.curves import PrecisionRecallCurve
        rec, prec, th = self.precision_recall_curve()
        return PrecisionRecallCurve(thresholds=[float(x) for x in th],
                                    precision=[float(x) for x in prec],
                                    recall=[float(x) for x in rec])


class ROCBinary:
    """Per-output independent binary ROC (reference: eval/ROCBinary.java)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self.rocs: list = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, float)
        predictions = np.asarray(predictions, float)
        n_out = labels.shape[-1]
        if not self.rocs:
            self.rocs = [ROC(self.threshold_steps) for _ in range(n_out)]
        for i in range(n_out):
            self.rocs[i].eval(labels[..., i], predictions[..., i], mask)
        return self

    def merge(self, other: "ROCBinary"):
        if not self.rocs:
            self.rocs = other.rocs
        else:
            for a, b in zip(self.rocs, other.rocs):
                a.merge(b)
        return self

    def calculate_auc(self, output: int) -> float:
        return self.rocs[output].calculate_auc()


class ROCMultiClass:
    """One-vs-all ROC per class (reference: eval/ROCMultiClass.java)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self.rocs: list = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, float)
        predictions = np.asarray(predictions, float)
        n_cls = labels.shape[-1]
        if not self.rocs:
            self.rocs = [ROC(self.threshold_steps) for _ in range(n_cls)]
        for i in range(n_cls):
            self.rocs[i].eval(labels[..., i], predictions[..., i], mask)
        return self

    def merge(self, other: "ROCMultiClass"):
        if not self.rocs:
            self.rocs = other.rocs
        else:
            for a, b in zip(self.rocs, other.rocs):
                a.merge(b)
        return self

    def calculate_auc(self, cls: int) -> float:
        return self.rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.rocs]))
