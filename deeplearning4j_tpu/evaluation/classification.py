"""Classification evaluation: accuracy/precision/recall/F1 + confusion matrix.

Reference: eval/Evaluation.java:72. Semantics match: predictions by argmax over the
class axis; precision/recall macro-averaged over classes with at least one true or
predicted example; masked timesteps excluded. Mergeable for distributed eval
(reference: IEvaluation.merge used by Spark map-reduce evaluation).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[list] = None, top_n: int = 1):
        """top_n > 1 additionally tracks top-N accuracy (prediction counts
        as correct if the true class is among the N highest-probability
        classes — reference: Evaluation.java:72 ``Evaluation(int topN)``,
        topNCorrectCount/topNTotalCount)."""
        self.num_classes = num_classes
        self.label_names = labels
        self.top_n = int(top_n)
        self.top_n_correct = 0
        self.top_n_total = 0
        self.confusion: Optional[np.ndarray] = None

    def _ensure(self, n):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = np.zeros((self.num_classes, self.num_classes), np.int64)

    def eval(self, labels, predictions, mask=None):
        """labels/predictions: [B, C] (one-hot / prob) or [B, T, C] time series."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        self._ensure(labels.shape[-1])
        true_idx = np.argmax(labels, axis=-1)
        pred_idx = np.argmax(predictions, axis=-1)
        np.add.at(self.confusion, (true_idx, pred_idx), 1)
        if self.top_n > 1:
            num_classes = predictions.shape[-1]
            if self.top_n >= num_classes:
                # top-N over all classes always contains the true class:
                # every example counts as correct (and argpartition's kth
                # would be out of range anyway)
                self.top_n_correct += int(true_idx.size)
            else:
                n = self.top_n
                top = np.argpartition(predictions, -n, axis=-1)[..., -n:]
                self.top_n_correct += int(
                    (top == true_idx[..., None]).any(-1).sum())
            self.top_n_total += int(true_idx.size)
        return self

    def merge(self, other: "Evaluation") -> "Evaluation":
        self.top_n_correct += other.top_n_correct
        self.top_n_total += other.top_n_total
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = other.confusion.copy()
        else:
            self.confusion += other.confusion
        return self

    # ---- metrics ----------------------------------------------------------
    def _counts(self):
        # nothing evaluated yet (e.g. a zero-batch worker in the
        # distributed-merge flow): every metric reads as 0, never crashes
        cm = (self.confusion if self.confusion is not None
              else np.zeros((1, 1), np.int64))
        tp = np.diag(cm).astype(float)
        fp = cm.sum(axis=0) - tp
        fn = cm.sum(axis=1) - tp
        return tp, fp, fn

    def accuracy(self) -> float:
        cm = self.confusion
        if cm is None:
            return 0.0  # nothing evaluated yet
        total = cm.sum()
        return float(np.diag(cm).sum() / total) if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        tp, fp, _ = self._counts()
        if cls is not None:
            if self.confusion is None:
                return 0.0  # zero state: like the aggregate metrics
            d = tp[cls] + fp[cls]
            return float(tp[cls] / d) if d else 0.0
        valid = (tp + fp) > 0
        if not valid.any():
            return 0.0
        return float(np.mean(tp[valid] / (tp[valid] + fp[valid])))

    def recall(self, cls: Optional[int] = None) -> float:
        tp, _, fn = self._counts()
        if cls is not None:
            if self.confusion is None:
                return 0.0  # zero state: like the aggregate metrics
            d = tp[cls] + fn[cls]
            return float(tp[cls] / d) if d else 0.0
        valid = (tp + fn) > 0
        if not valid.any():
            return 0.0
        return float(np.mean(tp[valid] / (tp[valid] + fn[valid])))

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def top_n_accuracy(self) -> float:
        """Fraction of examples whose true class is in the top-N predicted
        (reference: Evaluation.topNAccuracy). top_n=1 == accuracy()."""
        if self.top_n <= 1:
            return self.accuracy()
        return (self.top_n_correct / self.top_n_total
                if self.top_n_total else 0.0)

    def false_positive_rate(self, cls: int) -> float:
        if self.confusion is None:
            return 0.0  # zero state: like the aggregate metrics
        cm = self.confusion
        tp, fp, fn = self._counts()
        tn = cm.sum() - tp[cls] - fp[cls] - fn[cls]
        d = fp[cls] + tn
        return float(fp[cls] / d) if d else 0.0

    def to_json(self) -> str:
        """Serialize counts + config (reference: BaseEvaluation.toJson —
        the transport format for merging eval results across workers)."""
        import json
        return json.dumps({
            "@class": "Evaluation",
            "num_classes": self.num_classes,
            "labels": self.label_names,
            "top_n": self.top_n,
            "top_n_correct": self.top_n_correct,
            "top_n_total": self.top_n_total,
            "confusion": (self.confusion.tolist()
                          if self.confusion is not None else None),
        })

    @staticmethod
    def from_json(s: str) -> "Evaluation":
        import json
        d = json.loads(s)
        if d.get("@class") != "Evaluation":
            raise ValueError("not an Evaluation json")
        ev = Evaluation(num_classes=d["num_classes"], labels=d["labels"],
                        top_n=d["top_n"])
        ev.top_n_correct = d["top_n_correct"]
        ev.top_n_total = d["top_n_total"]
        if d["confusion"] is not None:
            ev.confusion = np.asarray(d["confusion"], np.int64)
        return ev

    def stats(self) -> str:
        names = self.label_names or [str(i) for i in range(self.num_classes or 0)]
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes: {self.num_classes}",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
        ] + ([f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}"]
             if self.top_n > 1 else []) + [
            "",
            "=========================Confusion Matrix=========================",
        ]
        if self.confusion is not None:
            header = "     " + " ".join(f"{n:>6}" for n in names)
            lines.append(header)
            for i, row in enumerate(self.confusion):
                lines.append(f"{names[i]:>4} " + " ".join(f"{v:>6}" for v in row))
        return "\n".join(lines)
