"""Regression evaluation (reference: eval/RegressionEvaluation.java:32): per-column
MSE/MAE/RMSE/correlation/R^2, mergeable via sufficient statistics."""

from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, num_columns: Optional[int] = None):
        self.n = 0
        self.num_columns = num_columns
        self._init_done = False

    def _ensure(self, c):
        if not self._init_done:
            self.num_columns = self.num_columns or c
            z = np.zeros(self.num_columns)
            self.sum_err2 = z.copy()
            self.sum_abs_err = z.copy()
            self.sum_l = z.copy()
            self.sum_p = z.copy()
            self.sum_l2 = z.copy()
            self.sum_p2 = z.copy()
            self.sum_lp = z.copy()
            self._init_done = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, float)
        predictions = np.asarray(predictions, float)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        self._ensure(labels.shape[-1])
        err = labels - predictions
        self.n += labels.shape[0]
        self.sum_err2 += (err ** 2).sum(axis=0)
        self.sum_abs_err += np.abs(err).sum(axis=0)
        self.sum_l += labels.sum(axis=0)
        self.sum_p += predictions.sum(axis=0)
        self.sum_l2 += (labels ** 2).sum(axis=0)
        self.sum_p2 += (predictions ** 2).sum(axis=0)
        self.sum_lp += (labels * predictions).sum(axis=0)
        return self

    def merge(self, other: "RegressionEvaluation"):
        if not getattr(other, "_init_done", False):
            return self
        if not self._init_done:
            self.__dict__.update({k: (v.copy() if isinstance(v, np.ndarray) else v)
                                  for k, v in other.__dict__.items()})
            return self
        self.n += other.n
        for k in ("sum_err2", "sum_abs_err", "sum_l", "sum_p", "sum_l2", "sum_p2",
                  "sum_lp"):
            setattr(self, k, getattr(self, k) + getattr(other, k))
        return self

    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs_err[col] / self.n)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.sum_err2[col] / self.n))

    def correlation_r2(self, col: int) -> float:
        n = self.n
        num = n * self.sum_lp[col] - self.sum_l[col] * self.sum_p[col]
        den = (np.sqrt(n * self.sum_l2[col] - self.sum_l[col] ** 2)
               * np.sqrt(n * self.sum_p2[col] - self.sum_p[col] ** 2))
        return float((num / den) ** 2) if den else 0.0

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self.sum_err2 / self.n))

    def stats(self) -> str:
        lines = ["Column    MSE            MAE            RMSE           R^2"]
        for c in range(self.num_columns):
            lines.append(f"col_{c}    {self.mean_squared_error(c):<14.6f} "
                         f"{self.mean_absolute_error(c):<14.6f} "
                         f"{self.root_mean_squared_error(c):<14.6f} "
                         f"{self.correlation_r2(c):<10.6f}")
        return "\n".join(lines)
