"""Device-resident fused evaluation: an epoch as a handful of dispatches.

The per-batch ``evaluate()`` path pays, per minibatch: one Python dispatch,
one host->device transfer, one FULL ``[B, T, C]`` logit fetch back to host,
and a numpy confusion-matrix build. The reference keeps eval a hot path too
— ParallelInference (parallelism/ParallelInference.java:33) serves it and
Spark map-reduces it (SparkDl4jMultiLayer.java:443-540) — so the fused-fit
treatment (optimize/fused_fit.py) is applied to the inference side here:

- ``build_fused_eval`` — ONE jitted, accumulator-donating program that runs
  forward + argmax + weighted scatter-add into a device-side accumulator
  (confusion matrix, top-N counters, loss sums), scanning K batches per
  dispatch (``lax.scan`` on TPU, trace-time unroll on CPU — the same
  ``_unroll_fused`` policy as training: XLA:CPU pessimizes compute inside
  ``while`` bodies).
- ``FusedEvalDriver`` — host-side block assembly with the fused-fit shape
  bucket (first usable batch fixes the bucket; undersized tails are padded
  up with replicated rows and ZERO eval weights, so counts are exactly
  those of the unpadded batch) plus double-buffered device prefetch via
  ``device_put_ahead``. An epoch of eval becomes ceil(n/K) dispatches and
  ONE small fetch (``num_classes**2`` ints + four scalars) instead of
  per-batch logit transfers.

Count semantics are exactly ``Evaluation.eval``'s: 2-D ``[B, C]`` labels
ignore any labels_mask (only synthesized pad rows get weight 0); 3-D
``[B, T, C]`` labels weight timesteps by the labels_mask. Top-N uses the
strictly-greater rank rule (true class counts when fewer than N classes
score strictly higher) — identical to numpy's argpartition membership
except on exact probability ties at the N-boundary.

Mesh evaluation (``parallel.evaluation.evaluate_on_mesh``) reuses the same
program with the batch axis sharded over the mesh: each device scatter-adds
its shard and XLA inserts the psum-style merge into the replicated
accumulator — ``IEvaluation.merge`` without ever leaving the device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.optimize.fused_fit import (
    DEFAULT_FUSED_STEPS,
    _unroll_fused,
    device_put_ahead,
)

#: CPU unroll width for eval. Larger than the training driver's CPU K=2:
#: the eval slot is forward-only (no gradient/updater code), so the
#: unrolled program stays small and a wider unroll keeps amortizing
#: dispatch overhead (measured on the CI host: K=2 -> 1.09x over the
#: per-batch path, K=8 -> 1.38x, K=16 -> 1.39x; 8 is the knee).
DEFAULT_EVAL_BATCHES_CPU = 8


def resolve_eval_batches(eval_batches) -> int:
    """Effective K (batches per eval dispatch). The rolled scan on TPU/GPU
    follows the fused training driver; CPU unrolls wider (see
    DEFAULT_EVAL_BATCHES_CPU)."""
    if eval_batches is None:
        return (DEFAULT_EVAL_BATCHES_CPU if jax.default_backend() == "cpu"
                else DEFAULT_FUSED_STEPS)
    k = int(eval_batches)
    if k < 1:
        raise ValueError(f"eval_batches must be >= 1, got {eval_batches}")
    return k


# ----------------------------------------------------------- per-batch stats
def build_eval_stats(net):
    """Per-batch eval forward for either network class.

    Returns ``stats(params, state, x, y, im) -> (probs, per_ex_loss)`` where
    ``probs`` is the output head's activation (what ``output()`` returns)
    and ``per_ex_loss`` is the loss head's per-example (or per-timestep)
    loss, or None when the net exposes no loss head. One forward pass feeds
    both — the loss is computed from the same pre-head activations."""
    layers = getattr(net, "layers", None)
    if isinstance(layers, list):
        from deeplearning4j_tpu.nn.conf.layers.misc import CenterLossOutputLayer

        out_idx = len(layers) - 1
        out_layer = layers[out_idx]

        def stats(params, state, x, y, im):
            last_in, _, _, cur_mask = net._forward(
                params, state, x, im, train=False, rng=None, upto=out_idx)
            if out_idx in net.conf.preprocessors:
                prep = net.conf.preprocessors[out_idx]
                last_in = prep.forward(last_in)
                cur_mask = prep.feed_forward_mask(cur_mask)
            p_out = params[str(out_idx)]
            probs, _ = out_layer.forward(
                p_out, state.get(str(out_idx), {}), last_in, mask=cur_mask,
                train=False)
            per_ex = None
            if hasattr(out_layer, "compute_loss_per_example"):
                if isinstance(out_layer, CenterLossOutputLayer):
                    per_ex = out_layer.compute_loss_per_example(
                        p_out, last_in, y, state=state.get(str(out_idx)))
                else:
                    per_ex = out_layer.compute_loss_per_example(
                        p_out, last_in, y)
            return probs, per_ex

        return stats

    # ComputationGraph: single-output classification, like its evaluate()
    out_name = net.conf.network_outputs[0]

    def stats(params, state, x, y, im):
        outs, _, _, _, loss_inputs = net._forward(
            params, state, [x], [im], train=False, rng=None,
            collect_loss_inputs=True)
        per_ex = None
        if out_name in loss_inputs:
            per_ex = net.conf.vertices[out_name].layer \
                .compute_loss_per_example(params.get(out_name, {}),
                                          loss_inputs[out_name], y)
        return outs[0], per_ex

    return stats


# ------------------------------------------------------- device accumulator
def init_accumulator(num_classes: int):
    """Fresh device-side accumulator. int32 counts (an epoch stays far below
    2**31 examples per class pair); cast to the Evaluation's int64 at the
    single end-of-epoch fetch."""
    return {
        "confusion": jnp.zeros((num_classes, num_classes), jnp.int32),
        "top_n_correct": jnp.zeros((), jnp.int32),
        "top_n_total": jnp.zeros((), jnp.int32),
        "loss_sum": jnp.zeros((), jnp.float32),
        "loss_weight": jnp.zeros((), jnp.float32),
    }


def _accumulate(acc, probs, y, ew, per_ex, top_n: int, num_classes: int):
    """Fold one batch into the accumulator. ``ew`` is the eval-weight array
    ([B] for 2-D labels, [B, T] for time series): 0 rows/steps (padding,
    masked timesteps) contribute nothing. The one-hot einsum form of the
    scatter-add reduces over the batch axis, so a mesh-sharded batch merges
    with one cross-device sum — the device-side ``Evaluation.merge``."""
    if probs.ndim == 3:
        p = probs.reshape(-1, probs.shape[-1])
        t = y.reshape(-1, y.shape[-1])
        w = ew.reshape(-1)
    else:
        p, t, w = probs, y, ew
    wi = (w != 0).astype(jnp.int32)
    true_idx = jnp.argmax(t, axis=-1)
    pred_idx = jnp.argmax(p, axis=-1)
    oh_true = jax.nn.one_hot(true_idx, num_classes, dtype=jnp.int32) \
        * wi[:, None]
    oh_pred = jax.nn.one_hot(pred_idx, num_classes, dtype=jnp.int32)
    out = dict(acc)
    out["confusion"] = acc["confusion"] + oh_true.T @ oh_pred
    if top_n > 1:
        if top_n >= num_classes:
            hit = jnp.ones_like(wi)  # top-N over all classes: always correct
        else:
            p_true = jnp.take_along_axis(p, true_idx[:, None], axis=-1)[:, 0]
            greater = jnp.sum((p > p_true[:, None]).astype(jnp.int32), -1)
            hit = (greater < top_n).astype(jnp.int32)
        out["top_n_correct"] = acc["top_n_correct"] + jnp.sum(hit * wi)
        out["top_n_total"] = acc["top_n_total"] + jnp.sum(wi)
    if per_ex is not None:
        wl = ew.reshape(per_ex.shape).astype(per_ex.dtype)
        out["loss_sum"] = acc["loss_sum"] + jnp.sum(per_ex * wl) \
            .astype(jnp.float32)
        out["loss_weight"] = acc["loss_weight"] + jnp.sum(wl) \
            .astype(jnp.float32)
    return out


def build_fused_eval(net, top_n: int, num_classes: int, mesh=None):
    """The fused K-batch eval program: ``program(params, state, acc, xs, ys,
    ims, ews) -> acc`` over ``[K, B, ...]`` stacks (``ims`` may be None —
    static, baked per jit signature). The accumulator is donated — it
    updates in place across the whole epoch. With ``mesh``, the batch axis
    (axis 1 of the stacks) is sharded over the mesh's data axis and the
    accumulator replicated; the reduction in ``_accumulate`` becomes the
    on-device merge."""
    stats = build_eval_stats(net)

    def block(params, state, acc, xs, ys, ims, ews):
        def slot(acc, inp):
            x, y, im, ew = inp
            probs, per_ex = stats(params, state, x, y, im)
            return _accumulate(acc, probs, y, ew, per_ex, top_n,
                               num_classes), None

        if _unroll_fused():
            for k in range(xs.shape[0]):  # static index -> straight-line HLO
                acc, _ = slot(acc, (xs[k], ys[k],
                                    None if ims is None else ims[k],
                                    ews[k]))
        else:
            acc, _ = lax.scan(slot, acc, (xs, ys, ims, ews))
        return acc

    if mesh is None:
        return jax.jit(block, donate_argnums=(2,))
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel.mesh import DATA_AXIS

    replicated = NamedSharding(mesh, P())
    batched = NamedSharding(mesh, P(None, DATA_AXIS))
    return jax.jit(
        block, donate_argnums=(2,),
        in_shardings=(replicated, replicated, replicated, batched, batched,
                      None, batched),
        out_shardings=replicated)


# ------------------------------------------------------------------- driver
class FusedEvalDriver:
    """Consumes a stream of DataSets as fused K-batch eval blocks.

    Shape bucketing follows ``FusedFitDriver``: the first usable batch fixes
    the bucket (batch size — rounded up to a mesh-worker multiple when
    sharded — plus trailing dims and mask signature); undersized batches are
    padded up by replicating the last row with ZERO eval weights. Tail
    groups of fewer than K batches run through a K=1 instance of the same
    program (one extra compile per stream, no dead-slot FLOPs). Batches
    that don't fit the bucket at all (different trailing dims, larger than
    bucket, missing labels) fall back to the host per-batch path — eval is
    a pure accumulation, so mixing paths cannot reorder anything.

    The end of the stream is ONE small fetch: the ``num_classes**2`` int
    confusion matrix plus four scalars, folded into the caller's
    ``Evaluation`` (and ``eval_loss`` — the masked mean loss the device
    accumulated for free — attached when the net has a loss head)."""

    def __init__(self, net, eval_batches: Optional[int] = None,
                 prefetch_depth: int = 2, mesh=None):
        self.net = net
        self.K = resolve_eval_batches(eval_batches)
        self.depth = max(1, prefetch_depth)
        self.mesh = mesh
        self._row_multiple = 1 if mesh is None else mesh.devices.size

    # ------------------------------------------------------------- assembly
    def _blocks(self, batches):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        bucket = None
        pend: list = []
        for ds in batches:
            item = None
            if (isinstance(ds, DataSet) and ds.labels is not None
                    and getattr(ds.labels, "ndim", 0) >= 2):
                f = np.asarray(ds.features)
                y = np.asarray(ds.labels)
                im = (None if ds.features_mask is None
                      else np.asarray(ds.features_mask))
                lm = (None if ds.labels_mask is None
                      else np.asarray(ds.labels_mask))
                if bucket is None:
                    B = -(-f.shape[0] // self._row_multiple) \
                        * self._row_multiple
                    bucket = (B, f.shape[1:], y.shape[1:], im is not None)
                B, ftail, ltail, has_im = bucket
                if (f.shape[1:] == ftail and y.shape[1:] == ltail
                        and (im is not None) == has_im
                        and f.shape[0] <= B):
                    item = self._pad_micro(f, y, im, lm, B)
            if item is not None:
                pend.append(item)
                if len(pend) == self.K:
                    yield ("block", self._stack(pend))
                    pend = []
            else:
                # pure accumulation: the host fallback can interleave freely
                yield ("raw", ds)
        for item in pend:
            # tail: K=1 instances of the same program (bucketed shapes, so
            # ONE extra compile per stream regardless of tail length)
            yield ("single", self._stack([item]))

    @staticmethod
    def _pad_micro(f, y, im, lm, B):
        n = f.shape[0]
        pad = B - n
        if y.ndim == 3:
            # time series: Evaluation.eval honors the labels_mask
            ew = (np.ones(y.shape[:2], np.float32) if lm is None
                  else np.asarray(lm, np.float32).reshape(y.shape[:2]))
        else:
            # 2-D labels: Evaluation.eval IGNORES any mask — only
            # synthesized pad rows get weight 0
            ew = np.ones((n,), np.float32)
        if pad:
            def rep(a):
                return np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])

            f, y = rep(f), rep(y)
            if im is not None:
                im = rep(im)
            ew = np.concatenate(
                [ew, np.zeros((pad,) + ew.shape[1:], ew.dtype)])
        return (f, y, im, ew)

    @staticmethod
    def _stack(items):
        def stack(j):
            if items[0][j] is None:
                return None
            return np.stack([r[j] for r in items])

        return (stack(0), stack(1), stack(2), stack(3))

    # ------------------------------------------------------------ execution
    def _place(self, tagged):
        tag, payload = tagged
        if tag == "raw":
            return tagged
        if self.mesh is None:
            return (tag, jax.device_put(payload))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS

        b = NamedSharding(self.mesh, P(None, DATA_AXIS))
        xs, ys, ims, ews = payload
        return (tag, (jax.device_put(xs, b), jax.device_put(ys, b),
                      None if ims is None else jax.device_put(ims, b),
                      jax.device_put(ews, b)))

    def _program(self, K, num_classes, top_n, xs, ys, ims):
        key = ("fused_eval", K, num_classes, top_n, xs.shape, ys.shape,
               ims is not None, None if self.mesh is None else self.mesh)
        return self.net._get_output(
            key, lambda: build_fused_eval(self.net, top_n, num_classes,
                                          mesh=self.mesh))

    def evaluate(self, batches, evaluation):
        """Evaluate the stream into ``evaluation`` (mutated and returned)."""
        net = self.net
        ev = evaluation
        top_n = getattr(ev, "top_n", 1)
        acc = None
        num_classes = None
        for tag, payload in device_put_ahead(self._blocks(batches),
                                             self.depth, self._place):
            if tag == "raw":
                ds = payload
                out = (net.output(ds.features, mask=ds.features_mask)
                       if hasattr(net, "layers") and isinstance(
                           net.layers, list)
                       else net.output(ds.features, masks=ds.features_mask))
                ev.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
                continue
            xs, ys, ims, ews = payload
            if acc is None:
                num_classes = ev.num_classes or ys.shape[-1]
                acc = init_accumulator(num_classes)
                if self.mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec as P
                    acc = jax.device_put(
                        acc, NamedSharding(self.mesh, P()))
            program = self._program(xs.shape[0], num_classes, top_n,
                                    xs, ys, ims)
            acc = program(net.params, net.state, acc, xs, ys, ims, ews)
        if acc is not None:
            # the ONE fetch: num_classes**2 ints + four scalars
            host = jax.tree_util.tree_map(np.asarray, acc)
            dev_ev = type(ev)(num_classes=num_classes, top_n=top_n)
            dev_ev.confusion = host["confusion"].astype(np.int64)
            dev_ev.top_n_correct = int(host["top_n_correct"])
            dev_ev.top_n_total = int(host["top_n_total"])
            ev.merge(dev_ev)
            if float(host["loss_weight"]) > 0:
                ev.eval_loss = float(host["loss_sum"]) \
                    / float(host["loss_weight"])
        return ev
