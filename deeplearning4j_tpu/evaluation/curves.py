"""Serializable evaluation curves (reference: eval/curves/ — BaseCurve
toJson/fromJson, RocCurve.java, PrecisionRecallCurve.java, Histogram.java).

Plain dataclasses + JSON: curves computed on one worker can be persisted,
shipped and re-plotted elsewhere (the reference round-trips them through
the UI stats storage the same way).
"""

from __future__ import annotations

import json
import math
from dataclasses import asdict, dataclass, field
from typing import List


def _finite(xs):
    """Non-finite floats (ROC's +inf sentinel threshold) serialize as null:
    bare ``Infinity`` is invalid RFC 8259 JSON and strict consumers
    (browser JSON.parse, jq, Java) reject the whole document."""
    return [None if isinstance(x, float) and not math.isfinite(x) else x
            for x in xs]


def _definite(xs):
    return [math.inf if x is None else x for x in xs]


@dataclass
class RocCurve:
    thresholds: List[float] = field(default_factory=list)
    fpr: List[float] = field(default_factory=list)
    tpr: List[float] = field(default_factory=list)

    def to_json(self) -> str:
        d = asdict(self)
        d["thresholds"] = _finite(d["thresholds"])
        return json.dumps({"@class": "RocCurve", **d}, allow_nan=False)

    @staticmethod
    def from_json(s: str) -> "RocCurve":
        d = json.loads(s)
        if d.pop("@class", "RocCurve") != "RocCurve":
            raise ValueError("not a RocCurve json")
        d["thresholds"] = _definite(d["thresholds"])
        return RocCurve(**d)

    def calculate_auc(self) -> float:
        import numpy as np
        fpr, tpr = np.asarray(self.fpr), np.asarray(self.tpr)
        order = np.argsort(fpr, kind="stable")
        return float(np.trapezoid(tpr[order], fpr[order]))


@dataclass
class PrecisionRecallCurve:
    thresholds: List[float] = field(default_factory=list)
    precision: List[float] = field(default_factory=list)
    recall: List[float] = field(default_factory=list)

    def to_json(self) -> str:
        d = asdict(self)
        d["thresholds"] = _finite(d["thresholds"])
        return json.dumps({"@class": "PrecisionRecallCurve", **d},
                          allow_nan=False)

    @staticmethod
    def from_json(s: str) -> "PrecisionRecallCurve":
        d = json.loads(s)
        if d.pop("@class", "PrecisionRecallCurve") != "PrecisionRecallCurve":
            raise ValueError("not a PrecisionRecallCurve json")
        d["thresholds"] = _definite(d["thresholds"])
        return PrecisionRecallCurve(**d)

    def calculate_auprc(self) -> float:
        import numpy as np
        rec, prec = np.asarray(self.recall), np.asarray(self.precision)
        order = np.argsort(rec, kind="stable")
        return float(np.trapezoid(prec[order], rec[order]))


@dataclass
class Histogram:
    """Field names match the dicts StatsListener._histograms emits and the
    UI histogram page consumes ({counts, min, max}), so a pipeline
    histogram round-trips through this class unchanged."""

    title: str = ""
    min: float = 0.0
    max: float = 1.0
    counts: List[int] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps({"@class": "Histogram", **asdict(self)})

    @staticmethod
    def from_json(s: str) -> "Histogram":
        d = json.loads(s)
        if d.pop("@class", "Histogram") != "Histogram":
            raise ValueError("not a Histogram json")
        return Histogram(**d)

    @staticmethod
    def from_stats(title: str, d: dict) -> "Histogram":
        """Wrap one StatsListener param_histograms entry."""
        return Histogram(title=title, min=d["min"], max=d["max"],
                         counts=list(d["counts"]))
