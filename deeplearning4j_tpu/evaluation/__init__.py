"""Evaluation: classification/regression metrics, ROC curves.

Reference: deeplearning4j-nn eval/ (19 files): Evaluation.java:72,
RegressionEvaluation.java:32, ROC.java:53, EvaluationBinary, curves/.
"""

from deeplearning4j_tpu.evaluation.classification import Evaluation
from deeplearning4j_tpu.evaluation.fused_eval import FusedEvalDriver
from deeplearning4j_tpu.evaluation.curves import (Histogram,
                                                  PrecisionRecallCurve,
                                                  RocCurve)
from deeplearning4j_tpu.evaluation.regression import RegressionEvaluation
from deeplearning4j_tpu.evaluation.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.evaluation.binary import EvaluationBinary
