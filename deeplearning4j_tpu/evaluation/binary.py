"""Per-output binary evaluation (reference: eval/EvaluationBinary.java):
independent TP/FP/TN/FN counts per output unit at threshold 0.5."""

from __future__ import annotations

import numpy as np


class EvaluationBinary:
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self._init_done = False

    def _ensure(self, n):
        if not self._init_done:
            self.tp = np.zeros(n)
            self.fp = np.zeros(n)
            self.tn = np.zeros(n)
            self.fn = np.zeros(n)
            self._init_done = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, float)
        predictions = np.asarray(predictions, float)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        self._ensure(labels.shape[-1])
        pred = predictions >= self.threshold
        lab = labels > 0.5
        self.tp += (pred & lab).sum(axis=0)
        self.fp += (pred & ~lab).sum(axis=0)
        self.tn += (~pred & ~lab).sum(axis=0)
        self.fn += (~pred & lab).sum(axis=0)
        return self

    def merge(self, other: "EvaluationBinary"):
        if not getattr(other, "_init_done", False):
            return self
        if not self._init_done:
            self.__dict__.update({k: (v.copy() if isinstance(v, np.ndarray) else v)
                                  for k, v in other.__dict__.items()})
            return self
        for k in ("tp", "fp", "tn", "fn"):
            setattr(self, k, getattr(self, k) + getattr(other, k))
        return self

    def accuracy(self, output: int) -> float:
        total = self.tp[output] + self.fp[output] + self.tn[output] + self.fn[output]
        return float((self.tp[output] + self.tn[output]) / total) if total else 0.0

    def precision(self, output: int) -> float:
        d = self.tp[output] + self.fp[output]
        return float(self.tp[output] / d) if d else 0.0

    def recall(self, output: int) -> float:
        d = self.tp[output] + self.fn[output]
        return float(self.tp[output] / d) if d else 0.0

    def f1(self, output: int) -> float:
        p, r = self.precision(output), self.recall(output)
        return 2 * p * r / (p + r) if (p + r) else 0.0
