"""TPU-VM fleet provisioning over the gcloud CLI.

Reference analog (deeplearning4j-scaleout/deeplearning4j-aws):
- ``Ec2BoxCreator`` (aws/ec2/Ec2BoxCreator.java — runInstances/blockUntilAll)
  -> ``TpuVmProvisioner``: create/list/delete TPU VMs and wait for READY.
- ``HostProvisioner`` / ``ClusterSetup`` (aws/ec2/provision/ — SSH file push
  + remote command runner + distributed launch)
  -> ``ClusterSetup``: push the training package to every worker of a pod
  slice and launch the ``jax.distributed`` run on all workers.
- ``S3Uploader`` / ``S3Downloader`` (aws/s3/) -> ``GcsTransfer`` via gsutil.

Everything builds explicit argv lists. ``dry_run=True`` records the argv
instead of executing, which is what the tests assert against — the same
commands run verbatim against a real project when ``dry_run=False``
(``gcloud`` must be on PATH; nothing in this module imports cloud SDKs).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import time
from typing import List, Optional, Sequence


class CommandRunner:
    """Executes (or, dry_run, records) argv lists. One seam for tests and
    for the real CLI; keeps provisioning logic free of subprocess details."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.history: List[List[str]] = []
        self.canned: dict = {}  # prefix tuple -> stdout (dry-run responses)

    def run(self, argv: Sequence[str], check: bool = True) -> str:
        argv = list(argv)
        self.history.append(argv)
        if self.dry_run:
            for prefix, out in self.canned.items():
                if tuple(argv[:len(prefix)]) == tuple(prefix):
                    return out
            return ""
        proc = subprocess.run(argv, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"command failed ({proc.returncode}): "
                f"{shlex.join(argv)}\n{proc.stderr}")
        return proc.stdout

    def script(self) -> str:
        """The recorded session as a copy-pasteable shell script."""
        return "\n".join(shlex.join(argv) for argv in self.history)


class TpuVmProvisioner:
    """Create / inspect / delete TPU VMs (reference: Ec2BoxCreator.create
    + blockTillAllRunning)."""

    def __init__(self, project: str, zone: str, runner: CommandRunner):
        self.project = project
        self.zone = zone
        self.runner = runner

    def _gcloud(self, *args: str) -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", *args,
                f"--project={self.project}", f"--zone={self.zone}",
                "--quiet"]

    def create(self, name: str, accelerator_type: str = "v5litepod-16",
               version: str = "v2-alpha-tpuv5-lite",
               preemptible: bool = False) -> None:
        argv = self._gcloud("create", name,
                            f"--accelerator-type={accelerator_type}",
                            f"--version={version}")
        if preemptible:
            argv.append("--preemptible")
        self.runner.run(argv)

    def describe(self, name: str) -> str:
        return self.runner.run(
            self._gcloud("describe", name, "--format=value(state)"))

    def wait_until_ready(self, name: str, timeout_s: float = 600,
                         poll_s: float = 10) -> None:
        """Poll until state == READY (Ec2BoxCreator.blockTillAllRunning)."""
        deadline = time.monotonic() + timeout_s
        while True:
            state = self.describe(name).strip()
            if state == "READY":
                return
            if self.runner.dry_run:
                return  # recorded the poll; nothing to wait for
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"TPU VM {name} not READY after {timeout_s}s "
                    f"(state={state!r})")
            time.sleep(poll_s)

    def delete(self, name: str) -> None:
        self.runner.run(self._gcloud("delete", name))

    def ssh(self, name: str, command: str,
            worker: str = "all") -> str:
        """Run a command on pod workers (HostProvisioner.runRemoteCommand)."""
        return self.runner.run(
            self._gcloud("ssh", name, f"--worker={worker}",
                         f"--command={command}"))

    def scp(self, name: str, local: str, remote: str,
            worker: str = "all") -> None:
        """Push a file or directory to pod workers
        (HostProvisioner.uploadFile). Directories (e.g. an unpacked
        training package) need gcloud's --recurse flag or the copy fails
        at runtime — a failure the dry-run argv tests cannot see."""
        extra = ["--recurse"] if os.path.isdir(local) else []
        self.runner.run(
            self._gcloud("scp", *extra, local, f"{name}:{remote}",
                         f"--worker={worker}"))


class GcsTransfer:
    """gsutil up/down (reference: s3/uploader/S3Uploader.java,
    s3/reader/S3Downloader.java)."""

    def __init__(self, runner: CommandRunner):
        self.runner = runner

    def upload(self, local: str, gcs_uri: str) -> None:
        if not gcs_uri.startswith("gs://"):
            raise ValueError(f"not a GCS uri: {gcs_uri}")
        self.runner.run(["gsutil", "-m", "cp", "-r", local, gcs_uri])

    def download(self, gcs_uri: str, local: str) -> None:
        if not gcs_uri.startswith("gs://"):
            raise ValueError(f"not a GCS uri: {gcs_uri}")
        self.runner.run(["gsutil", "-m", "cp", "-r", gcs_uri, local])


class ClusterSetup:
    """Provision a slice, push the training package, launch the distributed
    run on every worker (reference: ec2/provision/ClusterSetup.java +
    DistributedDeepLearningTrainer.java — whose flow is: create boxes,
    provision each over SSH, start the distributed job).

    On TPU pods the 'cluster' is one named slice whose workers already
    share ICI; the launch step runs the SAME command on every worker and
    jax.distributed derives rank/coordinator from the TPU metadata server,
    so no hand-rolled coordinator bootstrap is needed.
    """

    def __init__(self, project: str, zone: str, dry_run: bool = False):
        self.runner = CommandRunner(dry_run=dry_run)
        self.tpus = TpuVmProvisioner(project, zone, self.runner)
        self.gcs = GcsTransfer(self.runner)

    def provision(self, name: str, accelerator_type: str = "v5litepod-16",
                  version: str = "v2-alpha-tpuv5-lite",
                  package_path: Optional[str] = None,
                  pip_spec: str = "deeplearning4j_tpu") -> None:
        self.tpus.create(name, accelerator_type, version)
        self.tpus.wait_until_ready(name)
        if package_path is not None:
            self.tpus.ssh(name, "mkdir -p ~/pkg")
            self.tpus.scp(name, package_path, "~/pkg/")
            self.tpus.ssh(name, "pip install ~/pkg/*")
        else:
            self.tpus.ssh(name, f"pip install {pip_spec}")

    def launch(self, name: str, train_command: str) -> str:
        """Start ``train_command`` on all workers simultaneously — the
        ClusterSetup.java 'distributed launch' step."""
        return self.tpus.ssh(name, train_command, worker="all")

    def teardown(self, name: str) -> None:
        self.tpus.delete(name)
