"""Cloud provisioning for TPU training fleets.

The TPU-native analog of the reference's deeplearning4j-aws module
(deeplearning4j-scaleout/deeplearning4j-aws/): EC2 box creation + SSH
provisioning + S3 transfer become GCP TPU-VM lifecycle + SSH fan-out +
GCS transfer, all through the ``gcloud``/``gsutil`` CLIs.
"""

from deeplearning4j_tpu.cloud.provision import (
    ClusterSetup,
    GcsTransfer,
    TpuVmProvisioner,
)
