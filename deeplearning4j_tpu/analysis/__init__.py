"""graftcheck: AST-based JAX-hazard + concurrency static analysis.

Run it::

    python -m deeplearning4j_tpu.analysis --check

Programmatic entry points live in :mod:`deeplearning4j_tpu.analysis.core`
(:func:`~deeplearning4j_tpu.analysis.core.run_check`), the rule families
in :mod:`~deeplearning4j_tpu.analysis.jax_rules` and
:mod:`~deeplearning4j_tpu.analysis.concurrency_rules`, and the opt-in
runtime lock-order assertion in
:mod:`~deeplearning4j_tpu.analysis.instrument`.
"""

from deeplearning4j_tpu.analysis.core import (Baseline, Finding, Report,
                                              analyze, run_check)

__all__ = ["Baseline", "Finding", "Report", "analyze", "run_check"]
