"""JAX hazard rules for graftcheck.

Rules emitted by :func:`check_module`:

- ``jax-retrace-hazard`` — Python control flow (``if``/``while``/
  ``range()`` loop bound) on a *traced* parameter inside a function
  handed to ``jax.jit``. Every distinct concrete value retraces and
  recompiles the program; the serving perf story rests on occupancy
  changes NOT retracing. Static things are exempt: parameters named in
  ``static_argnums``/``static_argnames``, ``x is None`` checks (resolved
  at trace time), ``.shape``/``.ndim``/``.dtype``/``.size`` access, and
  ``isinstance``/``len``/``hasattr``/``callable`` calls — those are all
  trace-time constants.
- ``jax-varying-capture`` — a jitted function closes over a name its
  enclosing function reassigns in a loop or augments; each new value is
  baked in at trace time, so the jit either silently uses a stale value
  or retraces per call.
- ``jax-host-sync-in-hot-loop`` — ``.item()``, ``float()``, ``bool()``,
  ``int()``, ``np.asarray``/``np.array`` on a non-literal inside the
  decode/coalescer/fit hot loops. Each is a device→host sync that
  serializes the dispatch pipeline.
- ``jax-donation-misuse`` — an argument passed through a
  ``donate_argnums`` position is read again after the dispatch; the
  donated buffer is invalid once XLA reuses it.
- ``jax-untraced-randomness`` — ``np.random.*`` / ``random.*`` called
  inside a jitted body. The call runs once at trace time and bakes a
  constant into the program; ``jax.random`` with ``fold_in`` is the
  sanctioned path.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.core import Finding

# attribute access on a traced value that is still static at trace time
SAFE_ATTRS = {"shape", "ndim", "dtype", "size"}
# calls whose result on a traced value is a trace-time constant
SAFE_CALLS = {"isinstance", "len", "hasattr", "callable", "type", "getattr"}

# functions that ARE the serving/training hot loops; one host sync here
# stalls every slot/request in the batch
HOT_FUNCTIONS = {
    "_decode_once", "_prefill_wave",              # generation slot loop
    "_spec_decode_once",                          # speculative verify loop
    "_coalesce_loop", "_complete_loop",           # inference coalescer
    "_dispatch_batch", "_dispatch_fwd",           # inference dispatch
    "_run_block", "fit_stream",                   # fused-fit driver loop
    "_route_once", "_replica_done",               # fleet router hot path
    "_monitor_loop",                              # fleet redispatch/hedge
    "_service_parked",                            # fleet resume path
    "_snapshot_slot", "_adopt_into_slot",         # KV handoff export/adopt
    "_tier_route",                                # disagg tier routing
    "_transfer_loop",                             # prefill->decode export
    "_autoscale_tick",                            # autoscaler control loop
    "_soak_arrival_loop",                         # load-generator pacing
    "_snapshot_families",                         # /metrics scrape path
    "_proj",                                      # fused-dequant projection
    "_quantize_kv",                               # int8 KV write quantizer
    "_knn_coalesce_once",                         # knn query coalescer
    "_knn_dispatch_batch", "_dispatch_knn",       # knn search dispatch
    "_knn_complete_loop",                         # knn completer fetch
    "_paged_forward",                             # paged-KV decode read+write
    "paged_attend",                               # helper-seam dispatch
    "resolve_paged_backend",                      # helper-seam selection
    "_mesh_decode_once",                          # tensor-parallel decode tick
    "_shard_pool",                                # mesh pool placement
    "_reshard_snapshot",                          # adopt-side payload reshard
    "_sharded_write_attend",                      # shard_map write+attend body
    "_gossip_loop",                               # federation router tick
    "_route_host",                                # federation dispatch path
    "_harvest_host",                              # federation crash harvest
    "_rag_retrieve_done",                         # rag knn-tier completion
    "_rag_assemble_dispatch",                     # rag tier-boundary route
    "_rag_generate_done",                         # rag generate completion
    "_probe_local_rank",                          # per-device IVF probe body
}

SYNC_BUILTINS = {"float", "bool", "int"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'np.random.normal' for Attribute chains, 'float' for Names."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str_set(node: ast.AST) -> Set[str]:
    """Names out of a constant str / tuple-or-list of constant strs."""
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
    return out


def _const_int_set(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
    return out


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in getattr(a, "posonlyargs", [])]
    names += [p.arg for p in a.args]
    return names


def _jit_call_info(call: ast.Call, jit_names: Set[str]):
    """If ``call`` is jax.jit(target, ...) return (target_node,
    static_names, static_nums, donate_nums); else None."""
    name = _dotted(call.func)
    if name not in jit_names:
        return None
    target = call.args[0] if call.args else None
    static_names: Set[str] = set()
    static_nums: Set[int] = set()
    donate_nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static_names |= _const_str_set(kw.value)
        elif kw.arg == "static_argnums":
            static_nums |= _const_int_set(kw.value)
        elif kw.arg in ("donate_argnums", "donate_argnames"):
            donate_nums |= _const_int_set(kw.value)
    return target, static_names, static_nums, donate_nums


def _decorator_jit_info(dec: ast.AST, jit_names: Set[str]):
    """(static_names, static_nums) if ``dec`` is a jit decorator —
    bare ``@jax.jit``, ``@jax.jit(...)`` or ``@partial(jax.jit, ...)``."""
    if _dotted(dec) in jit_names:
        return set(), set()
    if isinstance(dec, ast.Call):
        fname = _dotted(dec.func)
        if fname in jit_names:
            info = _jit_call_info(dec, jit_names)
            return info[1], info[2]
        if fname in ("partial", "functools.partial") and dec.args \
                and _dotted(dec.args[0]) in jit_names:
            statics: Set[str] = set()
            nums: Set[int] = set()
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    statics |= _const_str_set(kw.value)
                elif kw.arg == "static_argnums":
                    nums |= _const_int_set(kw.value)
            return statics, nums
    return None


class _ModuleScan(ast.NodeVisitor):
    """Collects jit aliases and walks scopes, resolving which local
    function defs end up wrapped in jax.jit."""

    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: List[Finding] = []
        self.jit_names = {"jax.jit", "jit"}
        # scope bookkeeping: stack of (kind, name, node)
        self.scope: List[Tuple[str, str, ast.AST]] = []

    # ---- scope helpers -------------------------------------------------
    def _scope_name(self) -> str:
        names = [n for kind, n, _ in self.scope if kind in ("class", "func")]
        return ".".join(names) if names else "<module>"

    # ---- module entry --------------------------------------------------
    def run(self, tree: ast.Module) -> List[Finding]:
        for stmt in tree.body:
            if isinstance(stmt, ast.ImportFrom) and stmt.module == "jax":
                for alias in stmt.names:
                    if alias.name == "jit":
                        self.jit_names.add(alias.asname or "jit")
        self._walk_body(tree.body, local_defs={})
        return self.findings

    # ---- generic body walk: find defs, classify jit targets ------------
    @staticmethod
    def _scope_nodes(body):
        """Every node in this scope, NOT descending into nested
        def/class bodies (the nested def node itself is yielded). A def
        inside a `for`/`if` block still belongs to this scope."""
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _walk_body(self, body, local_defs: Dict[str, ast.AST]):
        """Scan one scope: (1) register its function defs (any nesting
        depth short of a nested scope), (2) resolve which of them get
        wrapped in jax.jit, (3) run the jitted checks and recurse."""
        nodes = list(self._scope_nodes(body))
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[node.name] = node

        jitted: Dict[str, Tuple[Set[str], Set[int]]] = {}
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            info = _jit_call_info(node, self.jit_names)
            if info is None:
                continue
            target, statics, nums, _don = info
            if isinstance(target, ast.Name) and target.id in local_defs:
                prev = jitted.get(target.id, (set(), set()))
                jitted[target.id] = (prev[0] | statics, prev[1] | nums)

        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                statics: Optional[Tuple[Set[str], Set[int]]] = None
                for dec in node.decorator_list:
                    got = _decorator_jit_info(dec, self.jit_names)
                    if got is not None:
                        statics = got
                        break
                if statics is None and node.name in jitted:
                    statics = jitted[node.name]
                if statics is not None:
                    self._check_jitted(node, statics[0], statics[1])
                self._enter_function(node, local_defs)
            elif isinstance(node, ast.ClassDef):
                self.scope.append(("class", node.name, node))
                self._walk_body(node.body, local_defs={})
                self.scope.pop()

    def _enter_function(self, fn, outer_defs: Dict[str, ast.AST]):
        self.scope.append(("func", fn.name, fn))
        if fn.name in HOT_FUNCTIONS:
            self._check_hot_loop(fn)
        self._check_donation(fn)
        # recurse into direct statement list (nested defs/classes)
        self._walk_body(fn.body, local_defs=dict(outer_defs))
        self.scope.pop()

    # ---- rule: retrace hazards inside a jitted def ---------------------
    def _check_jitted(self, fn, static_names: Set[str],
                      static_nums: Set[int]):
        params = _param_names(fn)
        traced = set(params) - static_names
        for i in static_nums:
            if 0 <= i < len(params):
                traced.discard(params[i])
        traced.discard("self")
        traced.discard("cls")

        scope = self._scope_name() + "." + fn.name \
            if self.scope else fn.name

        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue  # don't descend rule state into nested defs
            if isinstance(node, (ast.If, ast.While)):
                kind = "if" if isinstance(node, ast.If) else "while"
                for name in sorted(self._traced_in_test(node.test, traced)):
                    self.findings.append(Finding(
                        rule="jax-retrace-hazard", path=self.relpath,
                        line=node.lineno, col=node.col_offset, scope=scope,
                        detail=f"{fn.name}:{kind}:{name}",
                        message=(f"Python `{kind}` on traced parameter "
                                 f"`{name}` inside jitted `{fn.name}` — "
                                 "every distinct value retraces; use "
                                 "jnp.where/lax.cond or mark it static"),
                    ))
            elif isinstance(node, ast.For):
                it = node.iter
                if isinstance(it, ast.Call) and _dotted(it.func) == "range":
                    hazards = set()
                    for a in it.args:
                        hazards |= self._traced_in_test(a, traced)
                    for name in sorted(hazards):
                        self.findings.append(Finding(
                            rule="jax-retrace-hazard", path=self.relpath,
                            line=node.lineno, col=node.col_offset,
                            scope=scope, detail=f"{fn.name}:range:{name}",
                            message=(f"`range()` over traced parameter "
                                     f"`{name}` inside jitted `{fn.name}` "
                                     "— the loop unrolls per traced value;"
                                     " use lax.scan/fori_loop"),
                        ))
            elif isinstance(node, ast.Call):
                dn = _dotted(node.func)
                if dn and (dn.startswith("np.random.")
                           or dn.startswith("numpy.random.")
                           or dn.startswith("random.")):
                    self.findings.append(Finding(
                        rule="jax-untraced-randomness", path=self.relpath,
                        line=node.lineno, col=node.col_offset, scope=scope,
                        detail=f"{fn.name}:{dn}",
                        message=(f"`{dn}` inside jitted `{fn.name}` runs "
                                 "once at trace time and bakes a constant "
                                 "in — use jax.random with fold_in"),
                    ))

        self._check_varying_capture(fn, scope)

    def _traced_in_test(self, expr: ast.AST, traced: Set[str]) -> Set[str]:
        """Traced parameter names whose *value* the test depends on.
        `x is None`, `.shape`-family access, and isinstance/len/... calls
        are static at trace time and don't count."""
        out: Set[str] = set()

        def rec(e):
            if isinstance(e, ast.Name):
                if e.id in traced:
                    out.add(e.id)
            elif isinstance(e, ast.BoolOp):
                for v in e.values:
                    rec(v)
            elif isinstance(e, ast.UnaryOp):
                rec(e.operand)
            elif isinstance(e, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                    return  # identity vs None: concrete at trace time
                rec(e.left)
                for c in e.comparators:
                    rec(c)
            elif isinstance(e, ast.BinOp):
                rec(e.left)
                rec(e.right)
            elif isinstance(e, ast.Attribute):
                if e.attr in SAFE_ATTRS:
                    return  # x.shape[...] etc. are static
                rec(e.value)
            elif isinstance(e, ast.Subscript):
                rec(e.value)
                rec(e.slice)
            elif isinstance(e, ast.Call):
                if isinstance(e.func, ast.Name) and e.func.id in SAFE_CALLS:
                    return
                for a in e.args:
                    rec(a)
                for k in e.keywords:
                    rec(k.value)
            elif isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                for x in e.elts:
                    rec(x)
            elif isinstance(e, ast.IfExp):
                rec(e.test)
                rec(e.body)
                rec(e.orelse)

        rec(expr)
        return out

    # ---- rule: per-call-varying closure capture ------------------------
    def _check_varying_capture(self, fn, scope: str):
        encl = None
        for kind, _n, node in reversed(self.scope):
            if kind == "func":
                encl = node
                break
        if encl is None:
            return

        local: Set[str] = set(_param_names(fn))
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        local |= {p.arg for p in fn.args.kwonlyargs}
        loads: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, (ast.Store,)):
                    local.add(node.id)
                elif isinstance(node.ctx, ast.Load):
                    loads.add(node.id)
        free = loads - local

        # in the enclosing function (outside fn itself): does any free
        # name get augmented, or re-assigned inside a loop?
        varying: Dict[str, int] = {}

        def scan(node, in_loop: bool):
            if node is fn:
                return
            if isinstance(node, (ast.For, ast.While)):
                if isinstance(node, ast.For):
                    # the loop target itself varies per iteration
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name) and t.id in free:
                            varying.setdefault(t.id, node.lineno)
                for child in ast.iter_child_nodes(node):
                    scan(child, True)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not encl:
                return
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id in free:
                varying.setdefault(node.target.id, node.lineno)
            elif isinstance(node, ast.Assign) and in_loop:
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in free:
                        varying.setdefault(t.id, node.lineno)
            for child in ast.iter_child_nodes(node):
                scan(child, in_loop)

        scan(encl, False)
        for name in sorted(varying):
            self.findings.append(Finding(
                rule="jax-varying-capture", path=self.relpath,
                line=varying[name], col=0, scope=scope,
                detail=f"{fn.name}:{name}",
                message=(f"jitted `{fn.name}` closes over `{name}`, which "
                         f"`{encl.name}` rebinds per iteration — the jit "
                         "baked the trace-time value in; pass it as an "
                         "argument instead"),
            ))

    # ---- rule: host sync inside hot loops ------------------------------
    def _check_hot_loop(self, fn):
        scope = self._scope_name()  # fn already pushed on the stack
        seq: Dict[str, int] = {}   # occurrence index per call shape —
        # keeps the finding key stable while surrounding lines move
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dn = _dotted(node.func)
            hit = None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                hit = ".item()"
            elif dn in SYNC_BUILTINS and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant):
                hit = f"{dn}()"
            elif dn in ("np.asarray", "np.array",
                        "numpy.asarray", "numpy.array") and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                hit = dn
            if hit:
                seq[hit] = seq.get(hit, 0) + 1
                self.findings.append(Finding(
                    rule="jax-host-sync-in-hot-loop", path=self.relpath,
                    line=node.lineno, col=node.col_offset, scope=scope,
                    detail=f"{fn.name}:{hit}:{seq[hit]}",
                    message=(f"`{hit}` in hot loop `{fn.name}` forces a "
                             "device→host sync per iteration — batch the "
                             "fetch or keep the value on device"),
                ))

    # ---- rule: donated buffer read after dispatch ----------------------
    def _check_donation(self, fn):
        scope = self._scope_name()  # fn already pushed on the stack
        jit_fns: Dict[str, Set[int]] = {}
        # donated[text] = (line of donating call)
        donated: Dict[str, int] = {}

        events = []  # (line, col, kind, payload)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                info = _jit_call_info(node.value, self.jit_names)
                if info and info[3]:
                    events.append((node.lineno, node.col_offset, "jitdef",
                                   (node.targets[0].id, info[3])))
                    continue
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                events.append((node.lineno, node.col_offset, "call", node))
            if isinstance(node, (ast.Name, ast.Attribute)):
                text = _dotted(node)
                if text is None:
                    continue
                ctx = getattr(node, "ctx", None)
                kind = "store" if isinstance(ctx, ast.Store) else \
                    "load" if isinstance(ctx, ast.Load) else None
                if kind:
                    events.append((node.lineno, node.col_offset, kind,
                                   (text, node)))

        # order: within one line, loads/calls happen BEFORE the store of
        # an assignment target (`buf = step(buf, x)` rebinds AFTER the
        # donating call, so the donation is cleared, not reported)
        rank = {"jitdef": 0, "load": 1, "call": 2, "store": 3}
        events.sort(key=lambda e: (e[0], rank[e[2]], e[1]))
        # loads that are arguments of the donating call itself
        skip_loads: Set[int] = set()
        for line, col, kind, payload in events:
            if kind == "jitdef":
                name, dons = payload
                jit_fns[name] = dons
            elif kind == "call":
                call = payload
                fname = call.func.id
                if fname in jit_fns:
                    for pos in jit_fns[fname]:
                        if pos < len(call.args):
                            text = _dotted(call.args[pos])
                            if text:
                                donated[text] = line
                                for sub in ast.walk(call.args[pos]):
                                    skip_loads.add(id(sub))
            elif kind == "store":
                text, _node = payload
                donated.pop(text, None)
            elif kind == "load":
                text, node = payload
                if id(node) in skip_loads:
                    continue
                if text in donated and line > donated[text]:
                    self.findings.append(Finding(
                        rule="jax-donation-misuse", path=self.relpath,
                        line=line, col=col, scope=scope,
                        detail=f"{fn.name}:{text}",
                        message=(f"`{text}` was donated to a jitted call "
                                 f"(line {donated[text]}) and read again —"
                                 " the buffer may already be reused; "
                                 "rebind the output instead"),
                    ))
                    donated.pop(text, None)  # one finding per donation


def check_module(tree: ast.Module, relpath: str) -> List[Finding]:
    return _ModuleScan(relpath).run(tree)
