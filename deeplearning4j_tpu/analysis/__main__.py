"""CLI for graftcheck.

``python -m deeplearning4j_tpu.analysis --check`` scans the package
against the shipped baseline and exits non-zero on any unbaselined
finding (or any stale baseline entry — the audited list must not rot).
``--list`` prints every finding including baselined ones, ``--baseline``
points at an alternative baseline file, ``--root`` at an alternative
package directory.
"""

from __future__ import annotations

import argparse
import sys

from deeplearning4j_tpu.analysis.core import (DEFAULT_BASELINE, Baseline,
                                              analyze)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m deeplearning4j_tpu.analysis")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on unbaselined findings (default)")
    ap.add_argument("--list", action="store_true",
                    help="also print baselined findings with their "
                         "justifications")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path (default: the shipped one)")
    ap.add_argument("--root", default=None,
                    help="package directory to scan (default: the "
                         "installed deeplearning4j_tpu package)")
    args = ap.parse_args(argv)

    try:
        import os
        baseline = Baseline.load(args.baseline) \
            if os.path.exists(args.baseline) else Baseline()
    except ValueError as e:
        print(f"graftcheck: bad baseline: {e}", file=sys.stderr)
        return 2

    report = analyze(root=args.root, baseline=baseline)

    for err in report.parse_errors:
        print(f"graftcheck: parse error: {err}", file=sys.stderr)
    for f in report.unbaselined:
        print(f.render())
    if args.list:
        for f in report.baselined:
            just = baseline.entries.get(f.key, "")
            print(f"[baselined] {f.render()}  # {just}")
    for key in report.stale_baseline:
        print(f"graftcheck: stale baseline entry (matches nothing): {key}")

    n = len(report.unbaselined)
    print(f"graftcheck: {report.files_scanned} files, "
          f"{len(report.findings)} findings "
          f"({n} unbaselined, {len(report.baselined)} baselined, "
          f"{len(report.stale_baseline)} stale baseline entries)")
    if n or report.stale_baseline or report.parse_errors:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
