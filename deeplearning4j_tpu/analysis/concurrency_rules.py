"""Concurrency rules for graftcheck.

Rules emitted by :func:`check_module`:

- ``conc-mixed-lock`` — per-class lock-ownership inference. For every
  non-lock attribute of a class that constructs ``threading.Lock``/
  ``RLock``/``Condition`` members, accesses outside ``__init__`` are
  classified as locked/unlocked reads/writes. An attribute that is ever
  written AND is accessed both under and outside a lock is a finding:
  either the unlocked side races or the locked side is cargo cult.
  Private methods (``_name``) inherit the intersection of the lock sets
  held at their intra-class call sites, so ``_trip()`` called only with
  ``self._lock`` held does not false-positive.
- ``conc-lock-blocking-call`` — a blocking call (``Future.result``,
  ``queue.get``/``put``, ``.join``, ``Condition.wait`` on a *different*
  condition than the one held, ``block_until_ready``, ``sleep``,
  socket/HTTP I/O, retry loops) made while holding a lock. Everything
  else queued behind that lock stalls for the full wait.
- ``monotonic-deadline`` — ``time.time()`` used in arithmetic or
  comparisons (directly or via a local assigned from it). Wall clock
  jumps under NTP step/VM migration; durations and deadlines must use
  ``time.monotonic()``. Storing a wall timestamp (no arithmetic) is
  fine and not flagged.
- ``conc-loop-ownership`` — classes may declare
  ``_LOOP_OWNED = ("attr", ...)`` + ``_LOOP_LOCK = "lockname"`` at class
  level: state owned by the class's loop thread (the method handed to
  ``threading.Thread``/``ServingLoop`` as ``target=``/``tick=``/
  ``handler=``), read lock-free on that thread between rounds. Declared
  attributes are EXEMPT from ``conc-mixed-lock`` (the lock-free loop
  reads are the design), and in exchange every WRITE from a method
  reachable off the loop thread must hold the declared lock — a bare
  off-loop write is a finding, checked instead of baselined.

:func:`check_lock_graph` builds the cross-module lock-acquisition graph
(nodes = ``(Class, lock_attr)``; edges = "acquired while holding", via
nested ``with``, intra-class calls, and cross-object calls resolved
through ``self.x = ClassName(...)`` attribute types) and emits
``conc-lock-cycle`` for every cycle, naming the acquisition site of
every edge so a deadlock report is actionable.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.core import Finding

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
              "Lock", "RLock", "Condition"}

# receivers whose .get/.put we treat as queue ops even without a
# timeout/block kwarg
_QUEUEISH = ("q", "queue")

# method calls that mutate their receiver: self.xs.append(...) is a
# WRITE to xs for lock-ownership purposes
_MUTATORS = {"append", "appendleft", "pop", "popleft", "add", "remove",
             "discard", "clear", "update", "extend", "insert",
             "setdefault", "popitem"}

BLOCKING_ATTR_CALLS = {"result", "block_until_ready", "recv", "accept",
                       "sendall", "connect", "urlopen", "getresponse"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class _Access:
    attr: str
    write: bool
    held: FrozenSet[str]   # locally-held lock attrs at the access
    line: int


@dataclass
class _Acquire:
    lock: str
    held: FrozenSet[str]
    line: int


@dataclass
class _Call:
    node: ast.Call
    held: FrozenSet[str]
    line: int
    self_method: Optional[str]          # self.m(...)
    obj_attr: Optional[str] = None      # self.x.m(...) -> "x"
    obj_method: Optional[str] = None    # self.x.m(...) -> "m"


@dataclass
class _Method:
    name: str
    node: ast.AST
    accesses: List[_Access] = field(default_factory=list)
    acquires: List[_Acquire] = field(default_factory=list)
    calls: List[_Call] = field(default_factory=list)
    entry_held: FrozenSet[str] = frozenset()


@dataclass
class _Class:
    name: str
    path: str
    line: int
    locks: Set[str] = field(default_factory=set)
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, _Method] = field(default_factory=dict)
    loop_owned: Tuple[str, ...] = ()      # declared _LOOP_OWNED attrs
    loop_lock: Optional[str] = None       # declared _LOOP_LOCK name


# --------------------------------------------------------------------------
# per-class extraction
# --------------------------------------------------------------------------

def _scan_class(cls_node: ast.ClassDef, path: str) -> _Class:
    info = _Class(name=cls_node.name, path=path, line=cls_node.lineno)

    # pass 0: loop-ownership declarations (class-level literal assigns)
    for stmt in cls_node.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            continue
        tname = stmt.targets[0].id
        if tname == "_LOOP_OWNED" \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            info.loop_owned = tuple(
                e.value for e in stmt.value.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str))
        elif tname == "_LOOP_LOCK" \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            info.loop_lock = stmt.value.value

    # pass 1: lock members + attribute types from __init__
    for stmt in cls_node.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__":
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                if isinstance(node.value, ast.Call):
                    ctor = _dotted(node.value.func)
                    if ctor in LOCK_CTORS:
                        info.locks.add(tgt.attr)
                    elif ctor and ctor[:1].isupper():
                        # self.x = ClassName(...) — remember the type for
                        # cross-object lock-graph edges
                        info.attr_types[tgt.attr] = ctor.split(".")[-1]

    # pass 2: walk every method
    for stmt in cls_node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            m = _Method(name=stmt.name, node=stmt)
            _walk_method(stmt, info, m)
            info.methods[stmt.name] = m
    return info


def _walk_method(fn, cls: _Class, out: _Method) -> None:
    """Walk a method body tracking the set of locally-held lock attrs.
    Nested defs (retry closures) are walked with the held set at their
    definition site — they run in place on this stack in practice."""

    def lock_of(expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and expr.attr in cls.locks:
            return expr.attr
        return None

    def visit(node, held: FrozenSet[str]):
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                lk = lock_of(item.context_expr)
                if lk is not None:
                    out.acquires.append(
                        _Acquire(lock=lk, held=inner, line=node.lineno))
                    inner = inner | {lk}
                else:
                    visit(item.context_expr, held)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr not in cls.locks:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                out.accesses.append(_Access(node.attr, True, held,
                                            node.lineno))
            elif isinstance(node.ctx, ast.Load):
                out.accesses.append(_Access(node.attr, False, held,
                                            node.lineno))
            # no return: fall through to children (e.g. subscripts)
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)) \
                and isinstance(node.value, ast.Attribute) \
                and isinstance(node.value.value, ast.Name) \
                and node.value.value.id == "self" \
                and node.value.attr not in cls.locks:
            # self.xs[k] = ... / del self.xs[k] mutate the container
            out.accesses.append(_Access(node.value.attr, True, held,
                                        node.lineno))
        if isinstance(node, ast.AugAssign):
            tgt = node.target
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self" \
                    and tgt.attr not in cls.locks:
                # += is a read-modify-write
                out.accesses.append(_Access(tgt.attr, True, held,
                                            node.lineno))
                out.accesses.append(_Access(tgt.attr, False, held,
                                            node.lineno))
            visit(node.value, held)
            return
        if isinstance(node, ast.Call):
            self_method = None
            obj_attr = obj_method = None
            f = node.func
            if isinstance(f, ast.Attribute):
                if isinstance(f.value, ast.Name) and f.value.id == "self":
                    self_method = f.attr
                elif isinstance(f.value, ast.Attribute) \
                        and isinstance(f.value.value, ast.Name) \
                        and f.value.value.id == "self":
                    obj_attr, obj_method = f.value.attr, f.attr
                    if f.attr in _MUTATORS \
                            and obj_attr not in cls.locks:
                        # self.xs.append(...) mutates xs
                        out.accesses.append(_Access(obj_attr, True, held,
                                                    node.lineno))
            out.calls.append(_Call(node=node, held=held, line=node.lineno,
                                   self_method=self_method,
                                   obj_attr=obj_attr,
                                   obj_method=obj_method))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, frozenset())


def _propagate_entry_locks(cls: _Class, rounds: int = 3) -> None:
    """Private methods inherit the intersection of lock sets held at
    their intra-class call sites (public methods assume unlocked
    external callers). Fixed point over a few rounds handles private →
    private chains."""
    for _ in range(rounds):
        changed = False
        sites: Dict[str, List[FrozenSet[str]]] = {}
        for m in cls.methods.values():
            for c in m.calls:
                if c.self_method and c.self_method in cls.methods:
                    sites.setdefault(c.self_method, []).append(
                        c.held | m.entry_held)
        for name, m in cls.methods.items():
            if not name.startswith("_") or name.startswith("__"):
                continue  # public / dunder: callable from anywhere
            if name not in sites:
                continue
            entry = frozenset.intersection(*map(frozenset, sites[name]))
            if entry != m.entry_held:
                m.entry_held = entry
                changed = True
        if not changed:
            break


# --------------------------------------------------------------------------
# rule: conc-mixed-lock
# --------------------------------------------------------------------------

def _check_mixed_lock(cls: _Class) -> List[Finding]:
    findings: List[Finding] = []
    # attr -> [locked_any, unlocked_any, write_any, first unlocked line,
    #          lock names seen]
    stats: Dict[str, list] = {}
    for m in cls.methods.values():
        if m.name in ("__init__", "__del__"):
            continue  # construction/teardown are single-threaded
        for a in m.accesses:
            if a.attr in cls.loop_owned:
                continue  # covered by conc-loop-ownership instead
            held = a.held | m.entry_held
            st = stats.setdefault(a.attr, [False, False, False, None, set()])
            if held:
                st[0] = True
                st[4] |= set(held)
            else:
                st[1] = True
                if st[3] is None:
                    st[3] = a.line
            if a.write:
                st[2] = True
    for attr in sorted(stats):
        locked_any, unlocked_any, write_any, line, locks = stats[attr]
        if locked_any and unlocked_any and write_any:
            lk = "/".join(sorted("self." + l for l in locks))
            findings.append(Finding(
                rule="conc-mixed-lock", path=cls.path, line=line or cls.line,
                col=0, scope=cls.name, detail=attr,
                message=(f"attribute `{attr}` is accessed both under "
                         f"{lk} and with no lock held — the unlocked "
                         "side races with the locked writers"),
            ))
    return findings


# --------------------------------------------------------------------------
# rule: conc-loop-ownership
# --------------------------------------------------------------------------

def _loop_roots(cls: _Class) -> Set[str]:
    """Methods handed to a thread/loop constructor as its entrypoint:
    ``threading.Thread(target=self._m)``, ``ServingLoop(tick=self._m)``,
    ``ServingLoop(handler=self._m)``. (``wake=`` is excluded: wake hooks
    run on whichever thread advances the state machine.)"""
    roots: Set[str] = set()
    for m in cls.methods.values():
        for c in m.calls:
            fn = _dotted(c.node.func) or ""
            if fn.split(".")[-1] not in ("Thread", "ServingLoop"):
                continue
            for kw in c.node.keywords:
                if kw.arg in ("target", "tick", "handler") \
                        and isinstance(kw.value, ast.Attribute) \
                        and isinstance(kw.value.value, ast.Name) \
                        and kw.value.value.id == "self":
                    roots.add(kw.value.attr)
    return roots


def _reach(cls: _Class, seeds: Set[str]) -> Set[str]:
    """Transitive closure of intra-class ``self.m()`` calls."""
    seen = {s for s in seeds if s in cls.methods}
    frontier = list(seen)
    while frontier:
        name = frontier.pop()
        for c in cls.methods[name].calls:
            if c.self_method and c.self_method in cls.methods \
                    and c.self_method not in seen:
                seen.add(c.self_method)
                frontier.append(c.self_method)
    return seen


def _check_loop_ownership(cls: _Class) -> List[Finding]:
    """Writes to declared loop-owned attrs are legal (a) on the loop
    thread itself — methods reachable ONLY from the loop entrypoints —
    or (b) anywhere else under the declared loop lock. Anything else is
    exactly the race the mixed-lock exemption would otherwise hide."""
    if not cls.loop_owned or cls.loop_lock is None:
        return []
    public = {n for n in cls.methods if not n.startswith("_")}
    exclusive = _reach(cls, _loop_roots(cls)) - _reach(cls, public)
    findings: List[Finding] = []
    seen: Set[str] = set()
    for m in cls.methods.values():
        if m.name in ("__init__", "__del__") or m.name in exclusive:
            continue
        for a in m.accesses:
            if not a.write or a.attr not in cls.loop_owned:
                continue
            if cls.loop_lock in (a.held | m.entry_held):
                continue
            detail = f"{m.name}:{a.attr}"
            if detail in seen:
                continue
            seen.add(detail)
            findings.append(Finding(
                rule="conc-loop-ownership", path=cls.path, line=a.line,
                col=0, scope=f"{cls.name}.{m.name}", detail=detail,
                message=(f"loop-owned attribute `{a.attr}` written off "
                         f"the owning loop thread without `self."
                         f"{cls.loop_lock}` — the loop reads it "
                         "lock-free between rounds, so this write races"),
            ))
    return findings


# --------------------------------------------------------------------------
# rule: conc-lock-blocking-call
# --------------------------------------------------------------------------

def _blocking_kind(call: ast.Call, held: FrozenSet[str]) -> Optional[str]:
    f = call.func
    if not isinstance(f, ast.Attribute):
        fn = _dotted(f)
        if fn == "sleep":
            return "sleep()"
        return None
    name = f.attr
    recv = _dotted(f.value) or ""
    kwargs = {kw.arg for kw in call.keywords if kw.arg}

    if name in BLOCKING_ATTR_CALLS:
        return f".{name}()"
    if name == "sleep" or _dotted(f) in ("time.sleep",):
        return "time.sleep()"
    if name == "wait":
        # waiting on the condition you hold releases it — that's the
        # point of a Condition. Waiting on anything ELSE while holding
        # a lock is a stall.
        recv_attr = recv.split(".")[-1]
        if recv_attr in held:
            return None
        return f".wait() on `{recv}`"
    if name == "join":
        # thread.join() / thread.join(timeout) block; "sep".join(parts)
        # does not
        if not call.args and not kwargs:
            return ".join()"
        if "timeout" in kwargs:
            return ".join(timeout=...)"
        if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, (int, float)):
            return ".join(t)"
        return None
    if name in ("get", "put"):
        last = recv.split(".")[-1].lower()
        queueish = last.endswith(_QUEUEISH[0]) or _QUEUEISH[1] in last
        if queueish or "timeout" in kwargs or "block" in kwargs:
            return f".{name}() on queue `{recv}`"
        return None
    if name == "call" and "retry" in recv.split(".")[-1].lower():
        return f"`{recv}.call()` (sleeps between retries)"
    return None


def _check_blocking(cls: _Class) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[str] = set()
    for m in cls.methods.values():
        if m.name == "__init__":
            continue
        for c in m.calls:
            held = c.held | m.entry_held
            if not held:
                continue
            kind = _blocking_kind(c.node, held)
            if kind is None:
                continue
            lk = "/".join(sorted("self." + l for l in held))
            detail = f"{m.name}:{kind}"
            if detail in seen:
                continue  # one finding per (method, call shape)
            seen.add(detail)
            findings.append(Finding(
                rule="conc-lock-blocking-call", path=cls.path, line=c.line,
                col=c.node.col_offset, scope=f"{cls.name}.{m.name}",
                detail=detail,
                message=(f"blocking call {kind} while holding {lk} — "
                         "every thread queued on that lock stalls for "
                         "the full wait"),
            ))
    return findings


# --------------------------------------------------------------------------
# rule: monotonic-deadline
# --------------------------------------------------------------------------

def _contains_wall_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _dotted(sub.func) == "time.time":
            return True
    return False


def _check_monotonic(tree: ast.Module, relpath: str) -> List[Finding]:
    findings: List[Finding] = []

    def scan_fn(fn, scope: str):
        wall_names: Set[str] = set()
        ordered: List[ast.AST] = sorted(
            (n for n in ast.walk(fn) if hasattr(n, "lineno")),
            key=lambda n: (n.lineno, getattr(n, "col_offset", 0)))
        # first: names assigned from time.time() anywhere in fn
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _contains_wall_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wall_names.add(t.id)
        seen: Set[str] = set()
        for node in ordered:
            if not isinstance(node, (ast.BinOp, ast.Compare)):
                continue
            hit = None
            if _contains_wall_call(node):
                hit = "time.time()"
            else:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) \
                            and isinstance(sub.ctx, ast.Load) \
                            and sub.id in wall_names:
                        hit = sub.id
                        break
            if hit is None:
                continue
            detail = f"{getattr(fn, 'name', '<module>')}:{hit}"
            if detail in seen:
                continue
            seen.add(detail)
            findings.append(Finding(
                rule="monotonic-deadline", path=relpath, line=node.lineno,
                col=node.col_offset, scope=scope, detail=detail,
                message=(f"duration/deadline arithmetic on wall clock "
                         f"(`{hit}`) — wall time jumps under NTP; use "
                         "time.monotonic() for durations"),
            ))

    # top-level functions and methods (scan_fn covers their nested defs)
    class Top(ast.NodeVisitor):
        def __init__(self):
            self.stack: List[str] = []

        def visit_ClassDef(self, node):
            self.stack.append(node.name)
            for stmt in node.body:
                self.visit(stmt)
            self.stack.pop()

        def visit_FunctionDef(self, node):
            scan_fn(node, ".".join(self.stack + [node.name]))

        visit_AsyncFunctionDef = visit_FunctionDef

    Top().visit(tree)
    return findings


# --------------------------------------------------------------------------
# public entry points
# --------------------------------------------------------------------------

def _classes_of(tree: ast.Module, relpath: str) -> List[_Class]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cls = _scan_class(node, relpath)
            if cls.locks:
                _propagate_entry_locks(cls)
                out.append(cls)
    return out


def check_module(tree: ast.Module, relpath: str) -> List[Finding]:
    findings: List[Finding] = []
    for cls in _classes_of(tree, relpath):
        findings.extend(_check_mixed_lock(cls))
        findings.extend(_check_loop_ownership(cls))
        findings.extend(_check_blocking(cls))
    findings.extend(_check_monotonic(tree, relpath))
    return findings


def check_lock_graph(modules: List[Tuple[str, ast.Module]]) -> List[Finding]:
    """Cross-module pass: build the lock-acquisition graph and report
    every cycle with the acquisition site of each edge."""
    classes: Dict[str, _Class] = {}
    for relpath, tree in modules:
        for cls in _classes_of(tree, relpath):
            classes.setdefault(cls.name, cls)

    # method -> set of (lock, line) it acquires, incl. via self-calls
    acq: Dict[Tuple[str, str], Set[Tuple[str, int]]] = {}
    for cname, cls in classes.items():
        for mname, m in cls.methods.items():
            acq[(cname, mname)] = {(a.lock, a.line) for a in m.acquires}
    for _ in range(2):  # transitive through intra-class calls
        for cname, cls in classes.items():
            for mname, m in cls.methods.items():
                for c in m.calls:
                    if c.self_method and (cname, c.self_method) in acq:
                        acq[(cname, mname)] |= acq[(cname, c.self_method)]

    # edges: (src_node, dst_node) -> (path, line) acquisition site of dst
    edges: Dict[Tuple[Tuple[str, str], Tuple[str, str]],
                Tuple[str, int]] = {}

    def add_edge(src, dst, path, line):
        edges.setdefault((src, dst), (path, line))

    for cname, cls in classes.items():
        for m in cls.methods.values():
            for a in m.acquires:
                for h in (a.held | m.entry_held):
                    if h != a.lock:
                        add_edge((cname, h), (cname, a.lock),
                                 cls.path, a.line)
            for c in m.calls:
                held = c.held | m.entry_held
                if not held:
                    continue
                # cross-object: self.x.m() where x's class holds locks
                if c.obj_attr and c.obj_attr in cls.attr_types:
                    dname = cls.attr_types[c.obj_attr]
                    dcls = classes.get(dname)
                    if dcls is None:
                        continue
                    for (lk, line) in acq.get((dname, c.obj_method), ()):
                        for h in held:
                            add_edge((cname, h), (dname, lk),
                                     dcls.path, line)

    # cycle detection (DFS with colors); report each cycle once
    findings: List[Finding] = []
    graph: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for (src, dst) in edges:
        graph.setdefault(src, []).append(dst)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[Tuple[str, str], int] = {}
    stack: List[Tuple[str, str]] = []
    reported: Set[Tuple[Tuple[str, str], ...]] = set()

    def canon(cycle):
        i = cycle.index(min(cycle))
        return tuple(cycle[i:] + cycle[:i])

    def dfs(u):
        color[u] = GRAY
        stack.append(u)
        for v in graph.get(u, ()):
            if color.get(v, WHITE) == WHITE:
                dfs(v)
            elif color.get(v) == GRAY:
                cyc = canon(stack[stack.index(v):])
                if cyc in reported:
                    continue
                reported.add(cyc)
                parts = []
                ring = list(cyc) + [cyc[0]]
                for a, b in zip(ring, ring[1:]):
                    path, line = edges[(a, b)]
                    parts.append(f"{a[0]}.{a[1]} -> {b[0]}.{b[1]} "
                                 f"(acquired at {path}:{line})")
                first_path, first_line = edges[(ring[0], ring[1])]
                findings.append(Finding(
                    rule="conc-lock-cycle", path=first_path,
                    line=first_line, col=0, scope="<lock-graph>",
                    detail="->".join(f"{c}.{l}" for c, l in cyc),
                    message=("lock-order cycle: " + "; ".join(parts)
                             + " — two threads taking these in opposite "
                               "order deadlock"),
                ))
        stack.pop()
        color[u] = BLACK

    for node in list(graph):
        if color.get(node, WHITE) == WHITE:
            dfs(node)
    return findings
