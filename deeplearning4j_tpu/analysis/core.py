"""graftcheck core: findings, the baseline mechanism, and the runner.

The analyzer is the codebase-aware FindBugs/javac analog the reference
stack leaned on (SURVEY: the JVM scale-out layer survived because whole
bug classes were caught before runtime). Two rule families run over the
package's ASTs:

- ``jax_rules`` — retrace hazards, host-sync in hot loops, donation
  misuse, untraced randomness.
- ``concurrency_rules`` — per-class lock-ownership inference, a
  cross-module lock-acquisition graph with cycle detection,
  lock-held-across-blocking-call detection, and wall-clock duration
  math (the ``monotonic-deadline`` rule).

Findings carry a *stable key* (rule + file + scope + detail — no line
numbers), so the baseline survives unrelated edits. The baseline file is
the repo's audited list of known-unsafe spots: every entry needs a
one-line human justification, and the test gate fails on any finding
that is neither fixed nor baselined.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

#: repo-relative package directory the default run scans
DEFAULT_PACKAGE = "deeplearning4j_tpu"

#: baseline shipped with the package (the audited known-unsafe list)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


@dataclass
class Finding:
    """One analyzer hit. ``key`` intentionally omits the line number so a
    baseline entry keeps matching while surrounding code moves."""

    rule: str          # e.g. "conc-mixed-lock"
    path: str          # repo-relative posix path
    line: int
    col: int
    scope: str         # "Class.method", "function", or "<module>"
    detail: str        # rule-specific stable token (attr name, callee, ...)
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.scope}::{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")


@dataclass
class Baseline:
    """Audited suppressions: key -> justification. Every entry MUST carry
    a non-empty justification string — the baseline is documentation of
    deliberate unsafety, not a mute button."""

    entries: dict = field(default_factory=dict)
    path: Optional[str] = None

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as fh:
            raw = json.load(fh)
        entries = {}
        for e in raw.get("entries", []):
            key = e.get("key")
            just = e.get("justification", "")
            if not key:
                raise ValueError(f"baseline entry missing 'key': {e}")
            if not isinstance(just, str) or not just.strip():
                raise ValueError(
                    f"baseline entry for {key!r} has no justification — "
                    "every suppression must say WHY the spot is deliberate")
            entries[key] = just
        return cls(entries=entries, path=path)

    def match(self, finding: Finding) -> bool:
        return finding.key in self.entries

    def stale_keys(self, findings: Iterable[Finding]) -> List[str]:
        hit = {f.key for f in findings}
        return sorted(k for k in self.entries if k not in hit)


@dataclass
class Report:
    findings: List[Finding]        # everything the rules produced
    unbaselined: List[Finding]     # findings with no baseline entry
    baselined: List[Finding]
    stale_baseline: List[str]      # baseline keys matching nothing
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)


def _iter_py_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".cache")]
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def _relpath(path: str, base: str) -> str:
    return os.path.relpath(path, base).replace(os.sep, "/")


def analyze(root: Optional[str] = None,
            baseline: Optional[Baseline] = None,
            files: Optional[List[str]] = None) -> Report:
    """Run both rule families over ``root`` (a package directory) or an
    explicit ``files`` list. ``baseline`` splits findings into
    unbaselined (gate-failing) and baselined (audited)."""
    from deeplearning4j_tpu.analysis import concurrency_rules, jax_rules

    if root is None:
        root = os.path.join(_repo_root(), DEFAULT_PACKAGE)
    base = os.path.dirname(os.path.abspath(root))
    paths = files if files is not None else _iter_py_files(root)

    findings: List[Finding] = []
    parse_errors: List[str] = []
    modules = []  # (relpath, tree) pairs, for the cross-module pass
    for path in paths:
        rel = _relpath(path, base)
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError) as e:
            parse_errors.append(f"{rel}: {e}")
            continue
        modules.append((rel, tree))

    for rel, tree in modules:
        findings.extend(jax_rules.check_module(tree, rel))
        findings.extend(concurrency_rules.check_module(tree, rel))
    # the lock-acquisition graph needs every module's class info at once
    findings.extend(concurrency_rules.check_lock_graph(modules))

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if baseline is None:
        baseline = Baseline()
    unbase = [f for f in findings if not baseline.match(f)]
    based = [f for f in findings if baseline.match(f)]
    return Report(findings=findings, unbaselined=unbase, baselined=based,
                  stale_baseline=baseline.stale_keys(findings),
                  files_scanned=len(modules), parse_errors=parse_errors)


def _repo_root() -> str:
    # analysis/ lives at deeplearning4j_tpu/analysis/ — two dirs up is the
    # repo root the default scan is relative to
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_check(root: Optional[str] = None,
              baseline_path: Optional[str] = None) -> Report:
    """The CLI/test entry: scan the package against the shipped baseline
    (or ``baseline_path``)."""
    bp = baseline_path if baseline_path is not None else DEFAULT_BASELINE
    baseline = Baseline.load(bp) if os.path.exists(bp) else Baseline()
    return analyze(root=root, baseline=baseline)
