"""Opt-in runtime lock-order assertion (the dynamic half of graftcheck).

The static lock-acquisition graph (``concurrency_rules.check_lock_graph``)
proves the *declared* order is acyclic; this module asserts the order
actually holds at runtime. Every serving lock gets a rank, and acquiring
a lock whose rank is <= one already held by the thread raises
``LockOrderViolation`` naming both locks — a deadlock report BEFORE the
deadlock.

Enable it in tests with ``DL4J_TPU_LOCK_DEBUG=1``: conftest installs the
wrappers around the ``serving``/``generation`` test markers. Production
code never pays for it — ``install()`` rebinds the lock attributes after
construction; uninstalled classes use plain ``threading`` primitives.

The static order (low acquires first, a thread may only acquire UP):

====  =====================================
rank  lock
====  =====================================
10    StreamingBroker._lock
15    NearestNeighborsServer._lock
18    EmbeddingIndex._lock
20    ParallelInference._lock
25    ServingLoop._cond
28    GenerationServer._trace_lock
30    ParallelInference._drain_cv, GenerationServer._cond,
      EmbeddingIndex._drain_cv
35    ReplicaFleet._cond
38    FleetFederation._cond
40    KerasBackendServer._lock
55    LoopSupervisor._lock
60    AdmissionController._lock
70    CircuitBreaker._lock
80    RetryPolicy._lock
====  =====================================

The serving runtime slots in at 25: servers may touch their ServingLoop
(``begin_drain``/``close``/``put``) while holding a sub-25 lock, but the
re-homed servers always call the runtime with NO server lock held — the
runtime in turn invokes its callbacks (tick/handler/wake/on_death)
outside ``_cond``, so wake hooks may notify server conditions (rank
30/35) freely. The retrieval tier ranks lowest of the servers:
``NearestNeighborsServer`` handlers call into ``EmbeddingIndex``
(15 → 18) and the index's locked ``_ensure_workers`` starts/watches
runtime loops (18 → 25 → 55).
``GenerationServer._trace_lock`` (28) is the class-wide trace
serialization lock for mesh-sharded program builds: it is acquired with
no other lock held (program builds happen on the serving thread outside
``_cond``) and a build never touches ``_cond``, so it sits strictly
between the runtime (25) and the server conditions (30).
``ReplicaFleet._cond`` ranks above the replica servers'
locks because replica completion callbacks run under a server lock and
then take the fleet's. ``LoopSupervisor._lock`` ranks above every loop
and server lock it can be entered under (watch() from a locked
_ensure_workers); the supervisor copies its watch table under ``_lock``
and recovers loops outside it, so it never acquires downward.

(Serving stats counters moved into the per-metric leaf locks of the
metrics registry — metrics/registry.py — which rank strictly last:
registry publication never happens while holding a serving lock, and a
scrape takes no serving lock, so the registry stays out of the ranked
set.)
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_tls = threading.local()


class LockOrderViolation(AssertionError):
    """A thread acquired a lock out of rank order — two threads doing
    this in opposite order is a deadlock."""


def _stack() -> List[Tuple[int, int, str]]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _check_and_push(obj: "OrderedLock") -> None:
    st = _stack()
    held_max = max((r for (_i, r, _n) in st), default=None)
    if held_max is not None and obj.rank <= held_max:
        held = ", ".join(f"{n} (rank {r})" for (_i, r, n) in st)
        raise LockOrderViolation(
            f"acquiring {obj.name} (rank {obj.rank}) while holding "
            f"[{held}] — lock ranks must strictly increase; see "
            "deeplearning4j_tpu/analysis/instrument.py for the order")
    st.append((id(obj), obj.rank, obj.name))


def _pop(obj: "OrderedLock") -> None:
    st = _stack()
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] == id(obj):
            del st[i]
            return


def _push_unchecked(obj: "OrderedLock") -> None:
    _stack().append((id(obj), obj.rank, obj.name))


class OrderedLock:
    """Rank-checked wrapper over a ``threading.Lock``/``RLock``."""

    def __init__(self, rank: int, name: str, lock=None):
        self.rank = rank
        self.name = name
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, *a, **kw) -> bool:
        _check_and_push(self)
        got = self._lock.acquire(*a, **kw)
        if not got:
            _pop(self)
        return got

    def release(self) -> None:
        _pop(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class OrderedCondition(OrderedLock):
    """Rank-checked wrapper over a ``threading.Condition``. ``wait``
    pops the rank for its duration — the condition's lock is released
    while waiting, so holding the rank would false-positive the next
    acquisition on this thread."""

    def __init__(self, rank: int, name: str, cond=None):
        cond = cond if cond is not None else threading.Condition()
        super().__init__(rank, name, cond)
        self._cond = cond

    def wait(self, timeout: Optional[float] = None):
        _pop(self)
        try:
            return self._cond.wait(timeout)
        finally:
            _push_unchecked(self)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _pop(self)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _push_unchecked(self)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# ---------------------------------------------------------------------------
# install/uninstall: rebind the serving classes' lock attributes
# ---------------------------------------------------------------------------

#: class -> {attr: (rank, is_condition)}
def _targets() -> Dict[type, Dict[str, Tuple[int, bool]]]:
    from deeplearning4j_tpu.modelimport.server import KerasBackendServer
    from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex
    from deeplearning4j_tpu.nearestneighbors.server import (
        NearestNeighborsServer,
    )
    from deeplearning4j_tpu.parallel.federation import FleetFederation
    from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.parallel.resilience import (AdmissionController,
                                                        CircuitBreaker,
                                                        RetryPolicy)
    from deeplearning4j_tpu.parallel.runtime import (LoopSupervisor,
                                                     ServingLoop)
    from deeplearning4j_tpu.streaming.broker import StreamingBroker

    return {
        StreamingBroker: {"_lock": (10, False)},
        NearestNeighborsServer: {"_lock": (15, False)},
        EmbeddingIndex: {"_lock": (18, False), "_drain_cv": (30, True)},
        ParallelInference: {"_lock": (20, False), "_drain_cv": (30, True)},
        ServingLoop: {"_cond": (25, True)},
        GenerationServer: {"_cond": (30, True), "_trace_lock": (28, False)},
        ReplicaFleet: {"_cond": (35, True)},
        FleetFederation: {"_cond": (38, True)},
        KerasBackendServer: {"_lock": (40, False)},
        LoopSupervisor: {"_lock": (55, False)},
        AdmissionController: {"_lock": (60, False)},
        CircuitBreaker: {"_lock": (70, False)},
        RetryPolicy: {"_lock": (80, False)},
    }


_originals: List[Tuple[type, object]] = []


def install() -> None:
    """Wrap the serving classes' lock attributes in rank-checked
    wrappers (idempotent). New instances constructed after install()
    assert the static lock order on every acquisition."""
    if _originals:
        return
    for cls, attrs in _targets().items():
        orig_init = cls.__init__

        def make_init(orig, attr_map, cls_name):
            def __init__(self, *a, **kw):
                orig(self, *a, **kw)
                for attr, (rank, is_cond) in attr_map.items():
                    cur = getattr(self, attr, None)
                    if cur is None or isinstance(cur, OrderedLock):
                        continue
                    name = f"{cls_name}.{attr}"
                    wrapped = (OrderedCondition(rank, name, cur) if is_cond
                               else OrderedLock(rank, name, cur))
                    setattr(self, attr, wrapped)
            return __init__

        cls.__init__ = make_init(orig_init, attrs, cls.__name__)
        _originals.append((cls, orig_init))


def uninstall() -> None:
    """Restore the plain constructors (instances already wrapped keep
    their wrappers — they are behaviorally identical minus the check)."""
    while _originals:
        cls, orig = _originals.pop()
        cls.__init__ = orig
