"""MNIST dataset iterator.

Reference: deeplearning4j-core datasets/mnist/MnistManager.java (raw IDX parser) +
datasets/iterator/impl/MnistDataSetIterator.java. Reads standard IDX files from
``path`` (or $MNIST_DIR, or ~/.mnist). In a no-network environment with no files
present, falls back to a DETERMINISTIC SYNTHETIC digit-like dataset (class-dependent
oriented-bar patterns + noise) so end-to-end training, tests, and benchmarks run
offline; the synthetic task is learnable to >95% accuracy by LeNet.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

_FILES = {
    True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic, = struct.unpack(">I", data[:4])
    ndim = magic & 0xFF
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    return np.frombuffer(data, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _find_files(path: Optional[str], train: bool):
    candidates = [p for p in (path, os.environ.get("MNIST_DIR"),
                              os.path.expanduser("~/.mnist"),
                              os.path.expanduser("~/MNIST")) if p]
    img_name, lab_name = _FILES[train]
    for d in candidates:
        for suffix in ("", ".gz"):
            img = os.path.join(d, img_name + suffix)
            lab = os.path.join(d, lab_name + suffix)
            if os.path.exists(img) and os.path.exists(lab):
                return img, lab
    return None


def synthetic_mnist(n: int, seed: int = 123) -> DataSet:
    """Deterministic synthetic 28x28 10-class digit-like data.

    Each class is a distinct combination of an oriented bar and a blob position,
    plus pixel noise — linearly non-trivial, conv-learnable.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    xs = np.zeros((n, 28, 28), np.float32)
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32)
    for cls in range(10):
        idx = np.where(labels == cls)[0]
        if len(idx) == 0:
            continue
        angle = cls * np.pi / 10.0
        cx = 8.0 + 12.0 * ((cls * 7) % 10) / 10.0
        cy = 8.0 + 12.0 * ((cls * 3) % 10) / 10.0
        d = np.abs((xx - 14) * np.sin(angle) - (yy - 14) * np.cos(angle))
        bar = np.exp(-(d ** 2) / 6.0)
        blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2)) / 12.0)
        base = np.clip(bar + blob, 0, 1)
        jitter = rng.normal(0, 0.08, (len(idx), 28, 28)).astype(np.float32)
        shifts = rng.integers(-2, 3, (len(idx), 2))
        for j, i in enumerate(idx):
            img = np.roll(np.roll(base, shifts[j, 0], axis=0), shifts[j, 1], axis=1)
            xs[i] = np.clip(img + jitter[j], 0, 1)
    one_hot = np.eye(10, dtype=np.float32)[labels]
    return DataSet(xs.reshape(n, 784), one_hot)


class MnistDataSetIterator(ListDataSetIterator):
    """Flat [B, 784] features in [0,1], one-hot labels [B, 10].

    Matches the reference iterator's output contract
    (MnistDataSetIterator.java: binarize=false, normalize to [0,1]).
    """

    def __init__(self, batch_size: int = 128, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 123,
                 path: Optional[str] = None, shuffle: bool = False):
        found = _find_files(path, train)
        if found is not None:
            imgs = _read_idx(found[0]).astype(np.float32) / 255.0
            labs = _read_idx(found[1]).astype(np.int64)
            if num_examples:
                imgs, labs = imgs[:num_examples], labs[:num_examples]
            ds = DataSet(imgs.reshape(len(imgs), -1), np.eye(10, dtype=np.float32)[labs])
            self.synthetic = False
        else:
            n = num_examples or (60000 if train else 10000)
            ds = synthetic_mnist(n, seed=seed if train else seed + 1)
            self.synthetic = True
        super().__init__(ds, batch_size=batch_size, shuffle=shuffle, seed=seed)


class IrisDataSetIterator(ListDataSetIterator):
    """The classic Iris dataset, embedded (150 rows; reference:
    datasets/iterator/impl/IrisDataSetIterator.java). Features standardised."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150, seed: int = 42):
        x, y = _iris_data()
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(x))[:num_examples]
        x = (x - x.mean(axis=0)) / x.std(axis=0)
        ds = DataSet(x[idx].astype(np.float32), np.eye(3, dtype=np.float32)[y[idx]])
        super().__init__(ds, batch_size=batch_size)


def _iris_data():
    # Fisher's iris measurements (sepal l/w, petal l/w), classes 0/1/2 x 50.
    # Generated procedurally from the published per-class means/covariances is NOT
    # acceptable for exactness; the canonical 150 rows are embedded compactly.
    raw = (
        "5.1,3.5,1.4,.2;4.9,3,1.4,.2;4.7,3.2,1.3,.2;4.6,3.1,1.5,.2;5,3.6,1.4,.2;"
        "5.4,3.9,1.7,.4;4.6,3.4,1.4,.3;5,3.4,1.5,.2;4.4,2.9,1.4,.2;4.9,3.1,1.5,.1;"
        "5.4,3.7,1.5,.2;4.8,3.4,1.6,.2;4.8,3,1.4,.1;4.3,3,1.1,.1;5.8,4,1.2,.2;"
        "5.7,4.4,1.5,.4;5.4,3.9,1.3,.4;5.1,3.5,1.4,.3;5.7,3.8,1.7,.3;5.1,3.8,1.5,.3;"
        "5.4,3.4,1.7,.2;5.1,3.7,1.5,.4;4.6,3.6,1,.2;5.1,3.3,1.7,.5;4.8,3.4,1.9,.2;"
        "5,3,1.6,.2;5,3.4,1.6,.4;5.2,3.5,1.5,.2;5.2,3.4,1.4,.2;4.7,3.2,1.6,.2;"
        "4.8,3.1,1.6,.2;5.4,3.4,1.5,.4;5.2,4.1,1.5,.1;5.5,4.2,1.4,.2;4.9,3.1,1.5,.2;"
        "5,3.2,1.2,.2;5.5,3.5,1.3,.2;4.9,3.6,1.4,.1;4.4,3,1.3,.2;5.1,3.4,1.5,.2;"
        "5,3.5,1.3,.3;4.5,2.3,1.3,.3;4.4,3.2,1.3,.2;5,3.5,1.6,.6;5.1,3.8,1.9,.4;"
        "4.8,3,1.4,.3;5.1,3.8,1.6,.2;4.6,3.2,1.4,.2;5.3,3.7,1.5,.2;5,3.3,1.4,.2;"
        "7,3.2,4.7,1.4;6.4,3.2,4.5,1.5;6.9,3.1,4.9,1.5;5.5,2.3,4,1.3;6.5,2.8,4.6,1.5;"
        "5.7,2.8,4.5,1.3;6.3,3.3,4.7,1.6;4.9,2.4,3.3,1;6.6,2.9,4.6,1.3;5.2,2.7,3.9,1.4;"
        "5,2,3.5,1;5.9,3,4.2,1.5;6,2.2,4,1;6.1,2.9,4.7,1.4;5.6,2.9,3.6,1.3;"
        "6.7,3.1,4.4,1.4;5.6,3,4.5,1.5;5.8,2.7,4.1,1;6.2,2.2,4.5,1.5;5.6,2.5,3.9,1.1;"
        "5.9,3.2,4.8,1.8;6.1,2.8,4,1.3;6.3,2.5,4.9,1.5;6.1,2.8,4.7,1.2;6.4,2.9,4.3,1.3;"
        "6.6,3,4.4,1.4;6.8,2.8,4.8,1.4;6.7,3,5,1.7;6,2.9,4.5,1.5;5.7,2.6,3.5,1;"
        "5.5,2.4,3.8,1.1;5.5,2.4,3.7,1;5.8,2.7,3.9,1.2;6,2.7,5.1,1.6;5.4,3,4.5,1.5;"
        "6,3.4,4.5,1.6;6.7,3.1,4.7,1.5;6.3,2.3,4.4,1.3;5.6,3,4.1,1.3;5.5,2.5,4,1.3;"
        "5.5,2.6,4.4,1.2;6.1,3,4.6,1.4;5.8,2.6,4,1.2;5,2.3,3.3,1;5.6,2.7,4.2,1.3;"
        "5.7,3,4.2,1.2;5.7,2.9,4.2,1.3;6.2,2.9,4.3,1.3;5.1,2.5,3,1.1;5.7,2.8,4.1,1.3;"
        "6.3,3.3,6,2.5;5.8,2.7,5.1,1.9;7.1,3,5.9,2.1;6.3,2.9,5.6,1.8;6.5,3,5.8,2.2;"
        "7.6,3,6.6,2.1;4.9,2.5,4.5,1.7;7.3,2.9,6.3,1.8;6.7,2.5,5.8,1.8;7.2,3.6,6.1,2.5;"
        "6.5,3.2,5.1,2;6.4,2.7,5.3,1.9;6.8,3,5.5,2.1;5.7,2.5,5,2;5.8,2.8,5.1,2.4;"
        "6.4,3.2,5.3,2.3;6.5,3,5.5,1.8;7.7,3.8,6.7,2.2;7.7,2.6,6.9,2.3;6,2.2,5,1.5;"
        "6.9,3.2,5.7,2.3;5.6,2.8,4.9,2;7.7,2.8,6.7,2;6.3,2.7,4.9,1.8;6.7,3.3,5.7,2.1;"
        "7.2,3.2,6,1.8;6.2,2.8,4.8,1.8;6.1,3,4.9,1.8;6.4,2.8,5.6,2.1;7.2,3,5.8,1.6;"
        "7.4,2.8,6.1,1.9;7.9,3.8,6.4,2;6.4,2.8,5.6,2.2;6.3,2.8,5.1,1.5;6.1,2.6,5.6,1.4;"
        "7.7,3,6.1,2.3;6.3,3.4,5.6,2.4;6.4,3.1,5.5,1.8;6,3,4.8,1.8;6.9,3.1,5.4,2.1;"
        "6.7,3.1,5.6,2.4;6.9,3.1,5.1,2.3;5.8,2.7,5.1,1.9;6.8,3.2,5.9,2.3;6.7,3.3,5.7,2.5;"
        "6.7,3,5.2,2.3;6.3,2.5,5,1.9;6.5,3,5.2,2;6.2,3.4,5.4,2.3;5.9,3,5.1,1.8"
    )
    x = np.array([[float(v) for v in row.split(",")] for row in raw.split(";")])
    y = np.repeat(np.arange(3), 50)
    return x, y
