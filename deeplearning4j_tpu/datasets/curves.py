"""Curves dataset iterator (deep-autoencoder pretraining data).

Reference: deeplearning4j-core datasets/fetchers/CurvesDataFetcher.java —
downloads ``curves.ser`` (the Hinton deep-autoencoder "curves" set:
28x28 grayscale images of smooth curves drawn through random control
points) and serves it as DataSets whose labels equal the features
(reconstruction targets). This environment has no egress, so the curves
are GENERATED: deterministic quadratic Bezier chains through random
control points, rasterized at 28x28 with the same [0, 1] intensity
convention — the same unsupervised-pretraining workload, reproducible
from a seed instead of an S3 download. A local ``curves.npz`` (features
array, [N, 784] float) is used when present.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

SIDE = 28


def _rasterize_curve(rng: np.random.Generator) -> np.ndarray:
    """One 28x28 curve: a chain of quadratic Beziers through 4 random
    control points, drawn with bilinear splatting."""
    img = np.zeros((SIDE, SIDE), np.float32)
    pts = rng.uniform(3, SIDE - 3, size=(4, 2))
    # chain of quadratics: (p0,p1,p2), (p2,p3,reflected p1) keeps C1-ish
    segments = [(pts[0], pts[1], pts[2]), (pts[2], pts[3],
                                           2 * pts[3] - pts[1])]
    t = np.linspace(0.0, 1.0, 64)[:, None]
    for p0, p1, p2 in segments:
        xy = ((1 - t) ** 2) * p0 + 2 * (1 - t) * t * p1 + (t ** 2) * p2
        for x, y in xy:
            xi, yi = int(np.floor(x)), int(np.floor(y))
            fx, fy = x - xi, y - yi
            for dx, wx in ((0, 1 - fx), (1, fx)):
                for dy, wy in ((0, 1 - fy), (1, fy)):
                    xj, yj = xi + dx, yi + dy
                    if 0 <= xj < SIDE and 0 <= yj < SIDE:
                        img[yj, xj] = min(1.0, img[yj, xj] + wx * wy)
    return img


def synthetic_curves(n: int, seed: int = 7) -> np.ndarray:
    """[n, 784] float32 in [0, 1], deterministic in (n, seed)."""
    rng = np.random.default_rng(seed)
    return np.stack([_rasterize_curve(rng).ravel() for _ in range(n)])


def _find_file(path: Optional[str]):
    for d in (path, os.environ.get("CURVES_DIR"),
              os.path.expanduser("~/.curves")):
        if d:
            f = os.path.join(d, "curves.npz")
            if os.path.exists(f):
                return f
    return None


class CurvesDataSetIterator(ListDataSetIterator):
    """Batches of curve images with labels == features (the fetcher's
    reconstruction convention, CurvesDataFetcher.java:86 ``fetch`` slices
    the one curves DataSet)."""

    def __init__(self, batch_size: int = 128, num_examples: int = 2048,
                 path: Optional[str] = None, seed: int = 7):
        f = _find_file(path)
        if f is not None:
            feats = np.load(f)["features"][:num_examples].astype(np.float32)
        else:
            feats = synthetic_curves(num_examples, seed)
        batches = [DataSet(feats[i:i + batch_size], feats[i:i + batch_size])
                   for i in range(0, len(feats), batch_size)]
        super().__init__(batches, batch_size=batch_size)
