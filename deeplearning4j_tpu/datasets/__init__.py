"""Datasets: DataSet container, iterators, built-in datasets.

Reference: ND4J DataSet/MultiDataSet + deeplearning4j-core datasets/ (iterators,
MNIST fetcher, Iris), deeplearning4j-nn datasets/iterator/ (async prefetch).
"""

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    DevicePrefetchIterator,
    MultipleEpochsIterator,
)
from deeplearning4j_tpu.datasets.streaming import (
    ExampleCollator,
    QueueDataSetIterator,
    StreamingDataSetIterator,
)
from deeplearning4j_tpu.datasets.curves import CurvesDataSetIterator
