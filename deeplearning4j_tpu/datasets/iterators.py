"""DataSet iterators.

Reference: DataSetIterator contract + AsyncDataSetIterator (background prefetch
thread with a blocking queue, datasets/iterator/AsyncDataSetIterator.java:30,40 —
auto-wrapped inside fit at MultiLayerNetwork.java:1051-1053). The async variant here
does the same host-side prefetch so input pipeline time overlaps device compute; on
TPU the jitted step's async dispatch already overlaps one step, so the queue mainly
hides slow ETL (e.g. record readers / augmentation).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterable over DataSet minibatches; subclasses implement _generate()."""

    def __iter__(self):
        self.reset()
        return self._iterate()

    def _iterate(self):
        raise NotImplementedError

    def reset(self):
        pass

    def total_examples(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Batches over an in-memory DataSet or list of DataSets."""

    def __init__(self, data, batch_size: int = 32, shuffle: bool = False, seed: int = 0):
        if isinstance(data, (list, tuple)):
            data = DataSet.merge(list(data))
        self.data = data
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0

    def _iterate(self):
        data = self.data
        if self.shuffle:
            order = np.random.default_rng(self.seed + self._epoch).permutation(
                data.num_examples())
            self._epoch += 1
        else:
            order = np.arange(data.num_examples())
        for s in range(0, len(order), self.batch_size):
            idx = order[s:s + self.batch_size]
            yield DataSet(
                data.features[idx], data.labels[idx],
                None if data.features_mask is None else data.features_mask[idx],
                None if data.labels_mask is None else data.labels_mask[idx])

    def total_examples(self):
        return self.data.num_examples()


class AsyncDataSetIterator(DataSetIterator):
    """Wraps another iterator with a background prefetch thread + bounded queue."""

    def __init__(self, base: Iterable, queue_size: int = 4):
        self.base = base
        self.queue_size = queue_size

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def _iterate(self):
        q: queue.Queue = queue.Queue(maxsize=self.queue_size)
        DONE = object()
        err: list = []

        def worker():
            try:
                it = (self.base._iterate() if isinstance(self.base, DataSetIterator)
                      else iter(self.base))
                for ds in it:
                    q.put(ds)
            except BaseException as e:  # surface on the consumer side
                err.append(e)
            finally:
                q.put(DONE)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is DONE:
                break
            yield item
        t.join()
        if err:
            raise err[0]

    def total_examples(self):
        return self.base.total_examples() if hasattr(self.base, "total_examples") else None


class MultipleEpochsIterator(DataSetIterator):
    """Replays a base iterator N times as one pass (reference:
    datasets/iterator/MultipleEpochsIterator.java)."""

    def __init__(self, base: DataSetIterator, num_epochs: int):
        self.base = base
        self.num_epochs = num_epochs

    def reset(self):
        self.base.reset()

    def _iterate(self):
        for _ in range(self.num_epochs):
            self.base.reset()
            yield from self.base._iterate()


class SamplingDataSetIterator(DataSetIterator):
    """Random-with-replacement sampling batches from a DataSet."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int, seed: int = 0):
        self.data = data
        self.batch_size = batch_size
        self.total_batches = total_batches
        self.seed = seed
        self._calls = 0

    def _iterate(self):
        rng = np.random.default_rng(self.seed + self._calls)
        self._calls += 1
        n = self.data.num_examples()
        for _ in range(self.total_batches):
            idx = rng.integers(0, n, self.batch_size)
            yield DataSet(self.data.features[idx], self.data.labels[idx])
