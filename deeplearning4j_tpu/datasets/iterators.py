"""DataSet iterators.

Reference: DataSetIterator contract + AsyncDataSetIterator (background prefetch
thread with a blocking queue, datasets/iterator/AsyncDataSetIterator.java:30,40 —
auto-wrapped inside fit at MultiLayerNetwork.java:1051-1053). The async variant here
does the same host-side prefetch so input pipeline time overlaps device compute; on
TPU the jitted step's async dispatch already overlaps one step, so the queue mainly
hides slow ETL (e.g. record readers / augmentation).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterable over DataSet minibatches; subclasses implement _generate()."""

    def __iter__(self):
        self.reset()
        return self._iterate()

    def _iterate(self):
        raise NotImplementedError

    def reset(self):
        pass

    def total_examples(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Batches over an in-memory DataSet or list of DataSets."""

    def __init__(self, data, batch_size: int = 32, shuffle: bool = False, seed: int = 0):
        if isinstance(data, (list, tuple)):
            data = DataSet.merge(list(data))
        self.data = data
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0

    def _iterate(self):
        data = self.data
        if self.shuffle:
            order = np.random.default_rng(self.seed + self._epoch).permutation(
                data.num_examples())
            self._epoch += 1
        else:
            order = np.arange(data.num_examples())
        for s in range(0, len(order), self.batch_size):
            idx = order[s:s + self.batch_size]
            yield DataSet(
                data.features[idx], data.labels[idx],
                None if data.features_mask is None else data.features_mask[idx],
                None if data.labels_mask is None else data.labels_mask[idx])

    def total_examples(self):
        return self.data.num_examples()


class AsyncDataSetIterator(DataSetIterator):
    """Wraps another iterator with a background prefetch thread + bounded queue."""

    def __init__(self, base: Iterable, queue_size: int = 4):
        self.base = base
        self.queue_size = queue_size

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def _iterate(self):
        q: queue.Queue = queue.Queue(maxsize=self.queue_size)
        DONE = object()
        err: list = []

        def worker():
            try:
                it = (self.base._iterate() if isinstance(self.base, DataSetIterator)
                      else iter(self.base))
                for ds in it:
                    q.put(ds)
            except BaseException as e:  # surface on the consumer side
                err.append(e)
            finally:
                q.put(DONE)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is DONE:
                break
            yield item
        t.join()
        if err:
            raise err[0]

    def total_examples(self):
        return self.base.total_examples() if hasattr(self.base, "total_examples") else None


class DevicePrefetchIterator(DataSetIterator):
    """Keeps the next ``depth`` minibatches already ON DEVICE while the
    current one trains — the TPU-native second half of async prefetch.

    ``AsyncDataSetIterator`` overlaps host-side batch PRODUCTION with
    compute; this overlaps the host->device TRANSFER too. ``jax.device_put``
    dispatches asynchronously, so simply issuing the puts ``depth`` batches
    ahead pipelines the copies behind the running step — no extra thread
    needed (the flax ``prefetch_to_device`` pattern, expressed over the
    DataSetIterator contract; reference analog: AsyncDataSetIterator,
    datasets/iterator/AsyncDataSetIterator.java:30). The whole batch goes
    up as ONE ``device_put`` pytree call (one dispatch, not four).

    Measured caveat: the win depends on the backend's transfer path being
    the bottleneck. On a locally attached TPU this is the standard input
    pipeline; through the oversubscribed remote tunnel used for CI
    measurements, results swing with far-side contention (0.3x-1.3x
    observed within minutes of each other) — benchmark your own setup.

    ``sharding`` (optional ``jax.sharding.Sharding``) places each batch for
    mesh training — compose with ``ParallelWrapper``/``ShardedTrainer``
    data shardings.
    """

    def __init__(self, base: Iterable, depth: int = 2, sharding=None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.base = base
        self.depth = depth
        self.sharding = sharding

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def _put(self, ds):
        import jax

        from deeplearning4j_tpu.datasets.dataset import DataSet

        # ONE device_put over the whole batch pytree: a remote PJRT backend
        # pays per-dispatch latency, so 1 transfer call per batch beats 4
        arrs = tuple(None if a is None else np.asarray(a)
                     for a in (ds.features, ds.labels, ds.features_mask,
                               ds.labels_mask))
        if self.sharding is not None:
            # fail with a clear message on a trailing partial batch the
            # mesh cannot split — the raw jax error would surface `depth`
            # batches away from the offending data
            try:
                self.sharding.shard_shape(np.shape(arrs[0]))
            except ValueError as e:
                raise ValueError(
                    f"batch shape {np.shape(arrs[0])} is not divisible "
                    f"onto sharding {self.sharding} (trailing partial "
                    "batch? drop it or pad before prefetching)") from e
        placed = (jax.device_put(arrs, self.sharding)
                  if self.sharding is not None else jax.device_put(arrs))
        return DataSet.on_device(*placed)

    def _iterate(self):
        from deeplearning4j_tpu.optimize.fused_fit import device_put_ahead

        it = (self.base._iterate() if isinstance(self.base, DataSetIterator)
              else iter(self.base))
        return device_put_ahead(it, self.depth, self._put)

    def total_examples(self):
        return self.base.total_examples() \
            if hasattr(self.base, "total_examples") else None


class MultipleEpochsIterator(DataSetIterator):
    """Replays a base iterator N times as one pass (reference:
    datasets/iterator/MultipleEpochsIterator.java)."""

    def __init__(self, base: DataSetIterator, num_epochs: int):
        self.base = base
        self.num_epochs = num_epochs

    def reset(self):
        self.base.reset()

    def _iterate(self):
        for _ in range(self.num_epochs):
            self.base.reset()
            yield from self.base._iterate()


class SamplingDataSetIterator(DataSetIterator):
    """Random-with-replacement sampling batches from a DataSet."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int, seed: int = 0):
        self.data = data
        self.batch_size = batch_size
        self.total_batches = total_batches
        self.seed = seed
        self._calls = 0

    def _iterate(self):
        rng = np.random.default_rng(self.seed + self._calls)
        self._calls += 1
        n = self.data.num_examples()
        for _ in range(self.total_batches):
            idx = rng.integers(0, n, self.batch_size)
            yield DataSet(self.data.features[idx], self.data.labels[idx])
