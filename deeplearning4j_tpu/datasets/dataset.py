"""DataSet / MultiDataSet containers (reference: ND4J DataSet/MultiDataSet).

Arrays are host numpy; device transfer happens at the jitted-step boundary (the
reference's workspace/device-affinity machinery is unnecessary — XLA owns device
memory).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class DataSet:
    """features [B,...], labels [B,...], optional masks [B,T]."""

    def __init__(self, features, labels, features_mask=None, labels_mask=None):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    @classmethod
    def on_device(cls, features, labels=None, features_mask=None,
                  labels_mask=None) -> "DataSet":
        """Build a DataSet around already-placed jax arrays WITHOUT the
        base __init__'s np.asarray (which would pull them back to host).
        Used by device-prefetch and mesh-placement iterators."""
        ds = cls.__new__(cls)
        ds.features = features
        ds.labels = labels
        ds.features_mask = features_mask
        ds.labels_mask = labels_mask
        return ds

    def split_test_and_train(self, n_train: int):
        def cut(a, sl):
            return None if a is None else a[sl]

        return (DataSet(self.features[:n_train], cut(self.labels, slice(None, n_train)),
                        cut(self.features_mask, slice(None, n_train)),
                        cut(self.labels_mask, slice(None, n_train))),
                DataSet(self.features[n_train:], cut(self.labels, slice(n_train, None)),
                        cut(self.features_mask, slice(n_train, None)),
                        cut(self.labels_mask, slice(n_train, None))))

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, batch_size: int):
        n = self.num_examples()
        for s in range(0, n, batch_size):
            sl = slice(s, min(s + batch_size, n))
            yield DataSet(self.features[sl],
                          None if self.labels is None else self.labels[sl],
                          None if self.features_mask is None else self.features_mask[sl],
                          None if self.labels_mask is None else self.labels_mask[sl])

    @staticmethod
    def merge(datasets):
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            None if datasets[0].labels is None
            else np.concatenate([d.labels for d in datasets]),
            None if datasets[0].features_mask is None
            else np.concatenate([d.features_mask for d in datasets]),
            None if datasets[0].labels_mask is None
            else np.concatenate([d.labels_mask for d in datasets]))


class MultiDataSet:
    """Multiple feature/label arrays (reference: ND4J MultiDataSet, used by
    ComputationGraph multi-input/multi-output fit)."""

    def __init__(self, features, labels, features_masks=None, labels_masks=None):
        self.features = [np.asarray(f) for f in _as_list(features)]
        self.labels = [np.asarray(l) for l in _as_list(labels)]
        self.features_masks = ([None] * len(self.features) if features_masks is None
                               else [None if m is None else np.asarray(m)
                                     for m in _as_list(features_masks)])
        self.labels_masks = ([None] * len(self.labels) if labels_masks is None
                             else [None if m is None else np.asarray(m)
                                   for m in _as_list(labels_masks)])

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]
