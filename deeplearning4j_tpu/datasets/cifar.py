"""CIFAR-10 and LFW dataset iterators.

Reference: deeplearning4j-core datasets/iterator/impl/CifarDataSetIterator.java
(reads the CIFAR-10 binary batches) and LFWDataSetIterator.java (face images
by person directory). Both read standard on-disk formats when present and
fall back to DETERMINISTIC SYNTHETIC data offline (the MnistDataSetIterator
pattern in this package): class-conditioned color/texture fields that a CNN
can learn, so end-to-end pipelines run with zero network egress.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

CIFAR_LABELS = ["airplane", "automobile", "bird", "cat", "deer", "dog",
                "frog", "horse", "ship", "truck"]


def _find_cifar(path: Optional[str]):
    cands = [p for p in (path, os.environ.get("CIFAR_DIR"),
                         os.path.expanduser("~/.cifar"),
                         os.path.expanduser("~/cifar-10-batches-bin")) if p]
    for d in cands:
        if os.path.exists(os.path.join(d, "data_batch_1.bin")):
            return d
    return None


def _read_cifar_bin(path: str):
    """One CIFAR-10 binary batch: rows of [label, 3072 bytes CHW]."""
    raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
    labels = raw[:, 0].astype(np.int64)
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NHWC
    return imgs.astype(np.float32) / 255.0, labels


def synthetic_cifar(n: int, seed: int = 7) -> DataSet:
    """Deterministic 32x32x3 10-class synthetic data: each class a distinct
    dominant hue + oriented texture frequency, plus noise."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    xs = np.zeros((n, 32, 32, 3), np.float32)
    for cls in range(10):
        idx = np.where(labels == cls)[0]
        if idx.size == 0:
            continue
        hue = np.array([((cls * 37) % 10) / 10.0,
                        ((cls * 53) % 10) / 10.0,
                        ((cls * 71) % 10) / 10.0], np.float32)
        freq = 2.0 + cls
        angle = cls * np.pi / 10.0
        wave = 0.5 + 0.5 * np.sin(
            2 * np.pi * freq * (xx * np.cos(angle) + yy * np.sin(angle)))
        base = wave[..., None] * 0.6 + hue * 0.4
        noise = rng.normal(0, 0.07, (idx.size, 32, 32, 3)).astype(np.float32)
        xs[idx] = np.clip(base[None] + noise, 0, 1)
    return DataSet(xs, np.eye(10, dtype=np.float32)[labels])


class CifarDataSetIterator(ListDataSetIterator):
    """NHWC [B, 32, 32, 3] in [0,1], one-hot labels [B, 10] (reference:
    CifarDataSetIterator.java)."""

    def __init__(self, batch_size: int = 128, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 7,
                 path: Optional[str] = None, shuffle: bool = False):
        d = _find_cifar(path)
        if d is not None:
            files = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                     if train else ["test_batch.bin"])
            imgs, labels = zip(*[_read_cifar_bin(os.path.join(d, f))
                                 for f in files])
            imgs = np.concatenate(imgs)
            labels = np.concatenate(labels)
            if num_examples:
                imgs, labels = imgs[:num_examples], labels[:num_examples]
            ds = DataSet(imgs, np.eye(10, dtype=np.float32)[labels])
            self.synthetic = False
        else:
            n = num_examples or (50000 if train else 10000)
            ds = synthetic_cifar(n, seed=seed if train else seed + 1)
            self.synthetic = True
        super().__init__(ds, batch_size=batch_size, shuffle=shuffle,
                         seed=seed)


def synthetic_lfw(n: int, num_people: int, size: int, seed: int = 11
                  ) -> DataSet:
    """Face-like synthetic data: per-person characteristic ellipse geometry +
    tone."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_people, n)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    xs = np.zeros((n, size, size, 3), np.float32)
    for p in range(num_people):
        idx = np.where(labels == p)[0]
        if idx.size == 0:
            continue
        cx = 0.35 + 0.3 * ((p * 13) % num_people) / num_people
        cy = 0.35 + 0.3 * ((p * 29) % num_people) / num_people
        rx = 0.15 + 0.1 * ((p * 7) % num_people) / num_people
        ry = 0.2 + 0.1 * ((p * 17) % num_people) / num_people
        tone = 0.3 + 0.6 * ((p * 31) % num_people) / num_people
        face = np.exp(-(((xx - cx) / rx) ** 2 + ((yy - cy) / ry) ** 2))
        img = np.stack([face * tone, face * (1 - tone * 0.5),
                        face * (0.5 + tone * 0.3)], axis=-1)
        noise = rng.normal(0, 0.05,
                           (idx.size, size, size, 3)).astype(np.float32)
        xs[idx] = np.clip(img[None] + noise, 0, 1)
    return DataSet(xs, np.eye(num_people, dtype=np.float32)[labels])


class LFWDataSetIterator(ListDataSetIterator):
    """Labeled-faces-in-the-wild-style iterator (reference:
    LFWDataSetIterator.java). Reads person-per-directory images via
    ImageRecordReader when a root is given; synthetic offline otherwise."""

    def __init__(self, batch_size: int = 32, num_examples: int = 512,
                 image_size: int = 64, num_people: int = 10,
                 path: Optional[str] = None, seed: int = 11,
                 shuffle: bool = False):
        if path is not None and os.path.isdir(path):
            from deeplearning4j_tpu.datavec.records import ImageRecordReader
            rr = ImageRecordReader(image_size, image_size, 3, root=path)
            feats, labs = [], []
            for arr, lab in rr:
                feats.append(arr)
                labs.append(lab)
                if len(feats) >= num_examples:
                    break
            x = np.stack(feats)
            y = np.eye(rr.num_labels(), dtype=np.float32)[labs]
            ds = DataSet(x, y)
            self.synthetic = False
        else:
            ds = synthetic_lfw(num_examples, num_people, image_size,
                               seed=seed)
            self.synthetic = True
        super().__init__(ds, batch_size=batch_size, shuffle=shuffle,
                         seed=seed)
