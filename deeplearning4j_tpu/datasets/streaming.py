"""Streaming ingestion: train from unbounded push-style sources.

Reference: deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper
+ dl4j-streaming (Kafka/Camel routes feeding DataVec records into
DataSet iterators). TPU redesign: the broker client is out of scope (zero
egress on pods); what the framework owns is the BOUNDARY — a thread-safe
push queue a consumer thread feeds (the Kafka-poller analog) and a pull
iterator the training loop drains, with bounded-buffer backpressure so a
fast producer cannot overrun device memory, plus per-example->minibatch
collation (the DataVec record->DataSet step).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_DONE = object()


class QueueDataSetIterator(DataSetIterator):
    """Push side for producers, iterator side for training.

    A producer thread (e.g. a message-broker consumer) calls ``put(ds)``
    for each arriving minibatch and ``end()`` when the stream closes; the
    training loop iterates. ``put`` blocks once ``maxsize`` batches are
    buffered (backpressure). The iterator is single-pass: ``reset`` is a
    no-op by design — a stream has no beginning to return to (callers that
    need epochs buffer to a list first)."""

    def __init__(self, maxsize: int = 16):
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._ended = threading.Event()

    # ------------------------------------------------------------- producer
    def put(self, ds: DataSet, timeout: Optional[float] = None) -> None:
        if self._ended.is_set():
            raise RuntimeError("stream already ended")
        self._q.put(ds, timeout=timeout)

    def end(self) -> None:
        """Close the stream. Never blocks: the flag is authoritative (the
        consumer polls it), the sentinel is only a wake-up for a consumer
        currently parked in get() — skipped if the buffer is full, in
        which case the consumer is not parked."""
        self._ended.set()
        try:
            self._q.put_nowait(_DONE)
        except queue.Full:
            pass

    # ------------------------------------------------------------- consumer
    def _iterate(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._ended.is_set():
                    return  # drained after end(): later passes end too
                continue
            if item is _DONE:
                return
            yield item

    def reset(self):  # single-pass stream
        pass


class StreamingDataSetIterator(DataSetIterator):
    """Pull from a (possibly slow/unbounded) source with a bound on total
    batches per pass. ``source`` may be any iterable/generator of DataSets
    — a socket reader, a file tailer, a generator polling an external
    queue. ``max_batches`` bounds one training pass over an endless
    stream (reference: Spark streaming's per-interval micro-batching)."""

    def __init__(self, source: Iterable, max_batches: Optional[int] = None):
        self.source = source
        self.max_batches = max_batches
        self._it = None

    def _iterate(self):
        if self._it is None:
            self._it = iter(self.source)
        n = 0
        for ds in self._it:
            yield ds
            n += 1
            if self.max_batches is not None and n >= self.max_batches:
                return

    def reset(self):
        # continue the stream; a fresh pass picks up where the last ended
        # (resetting a stream to its start is meaningless)
        pass


class ExampleCollator:
    """Collate single examples into fixed-size minibatches (the DataVec
    record -> DataSet step of the reference's streaming route). Push
    ``add(features, label)`` per arriving record; completed batches come
    out of ``batches()`` / flow into an attached QueueDataSetIterator."""

    def __init__(self, batch_size: int, sink: Optional[QueueDataSetIterator] = None):
        self.batch_size = batch_size
        self.sink = sink
        self._f: list = []
        self._l: list = []
        self._lock = threading.Lock()

    def add(self, features, label) -> Optional[DataSet]:
        with self._lock:
            self._f.append(np.asarray(features))
            self._l.append(np.asarray(label))
            if len(self._f) < self.batch_size:
                return None
            ds = DataSet(np.stack(self._f), np.stack(self._l))
            self._f, self._l = [], []
        if self.sink is not None:
            self.sink.put(ds)
        return ds

    def flush(self) -> Optional[DataSet]:
        """Emit the trailing partial batch, if any."""
        with self._lock:
            if not self._f:
                return None
            ds = DataSet(np.stack(self._f), np.stack(self._l))
            self._f, self._l = [], []
        if self.sink is not None:
            self.sink.put(ds)
        return ds
