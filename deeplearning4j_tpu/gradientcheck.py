"""Finite-difference gradient checking.

Reference: gradientcheck/GradientCheckUtil.java:41-80,77,238,401 — the correctness
backbone of the reference's test suite. Method identical: numerical gradient
(C(w+eps) - C(w-eps)) / (2 eps) per parameter vs the analytic gradient, relative
error |a-n| / max(|a|, |n|) must be below ``max_rel_error`` (absolute-error escape
hatch for near-zero grads). Here the analytic gradient is jax.grad of the same loss
— so this validates every layer's forward is differentiated correctly, replacing the
reference's per-layer hand-written backpropGradient checks.

Run under float64 (tests enable jax_enable_x64) for meaningful tolerances.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from deeplearning4j_tpu.utils.pytree import flatten_params, unflatten_params


def check_gradients(net, x, y, input_mask=None, label_mask=None, *, eps: float = 1e-6,
                    max_rel_error: float = 1e-5, min_abs_error: float = 1e-8,
                    subset: Optional[int] = None, seed: int = 0, train: bool = True,
                    verbose: bool = False) -> bool:
    """Returns True if all checked parameter gradients pass."""
    import jax.numpy as jnp

    def _to_jnp(a):
        if a is None:
            return None
        if isinstance(a, (list, tuple)):  # multi-input/-output graphs
            return [None if e is None else jnp.asarray(e) for e in a]
        return jnp.asarray(a)

    x = _to_jnp(x)
    y = _to_jnp(y)
    im = _to_jnp(input_mask)
    lm = _to_jnp(label_mask)
    params0 = net.params
    layers = getattr(net, "layers", None)

    def loss_of(params):
        loss, _ = net._loss(params, net.state, x, y, im, lm, train=train, rng=None)
        return loss

    loss_jit = jax.jit(loss_of)
    analytic_tree = jax.grad(loss_of)(params0)
    # the loss stop_gradients the l1/l2 penalty and the train step adds its
    # closed form instead; mirror that here so the analytic side matches
    # what training uses — the finite differences naturally include the
    # penalty
    from deeplearning4j_tpu.nn.regularization import add_regularization_grads

    analytic_tree = add_regularization_grads(net, params0, analytic_tree)
    if isinstance(layers, list):
        analytic = flatten_params(analytic_tree, layers).astype(np.float64)
        flat0 = flatten_params(params0, layers).astype(np.float64)

        def unflatten(flat):
            return unflatten_params(flat, params0, layers)
    else:
        # graph nets: order-agnostic flat view via ravel_pytree
        from jax.flatten_util import ravel_pytree

        flat0_j, unravel = ravel_pytree(params0)
        flat0 = np.asarray(flat0_j).astype(np.float64)
        analytic = np.asarray(ravel_pytree(analytic_tree)[0]).astype(np.float64)

        def unflatten(flat):
            return unravel(jnp.asarray(flat, dtype=flat0_j.dtype))

    n = flat0.size
    if subset is not None and subset < n:
        idxs = np.random.default_rng(seed).choice(n, subset, replace=False)
    else:
        idxs = np.arange(n)

    def loss_flat(flat):
        return float(loss_jit(unflatten(flat)))

    n_fail = 0
    max_err = 0.0
    for i in idxs:
        plus = flat0.copy()
        plus[i] += eps
        minus = flat0.copy()
        minus[i] -= eps
        numeric = (loss_flat(plus) - loss_flat(minus)) / (2.0 * eps)
        a = analytic[i]
        denom = max(abs(a), abs(numeric))
        rel = abs(a - numeric) / denom if denom > 0 else 0.0
        max_err = max(max_err, rel)
        if rel > max_rel_error and abs(a - numeric) > min_abs_error:
            n_fail += 1
            if verbose:
                print(f"param {i}: analytic={a:.8g} numeric={numeric:.8g} rel={rel:.3g}")
    if verbose:
        print(f"checked {len(idxs)}/{n} params, max rel error {max_err:.3g}, "
              f"{n_fail} failures")
    return n_fail == 0
