"""Seeded closed-loop load harness for the serving layer.

Two arrival processes:

- **open** — a non-homogeneous Poisson process: arrival times are
  precomputed by Lewis-Shedler thinning from a seeded RNG and a rate
  profile, then replayed against the wall clock. Latency is measured
  from the *scheduled* arrival, not the actual submit, so a stalled
  server inflates the tail instead of silently pacing the generator
  down (no coordinated omission).
- **closed** — N workers in submit-wait loops with optional think
  time; concurrency is the knob, rate is emergent.

Profiles are plain ``rate(t)`` callables; ``ramp_profile`` and
``spike_profile`` build the two shapes ``bench.py serve_soak``
composes. Everything is deterministic under a fixed seed: the same
schedule, the same request indices, the same reservoir sampling.

The generator publishes into its own registry (``soak_latency_ms``
histogram, submitted/completed/failed counters) and returns a
:class:`LoadResult` with the SLO inputs: quantiles, achieved
throughput, error taxonomy, and the zero-lost-futures check
(``submitted == completed + failed``).
"""

from __future__ import annotations

import random
import threading
import time

from deeplearning4j_tpu.metrics.registry import MetricsRegistry

__all__ = ["LoadGenerator", "LoadResult", "ramp_profile", "spike_profile",
           "poisson_arrivals"]


def ramp_profile(lo, hi, ramp_s):
    """Rate climbs linearly from ``lo`` to ``hi`` over ``ramp_s``,
    then holds at ``hi``."""
    span = max(ramp_s, 1e-9)

    def rate(t):
        frac = min(1.0, max(0.0, t / span))
        return lo + (hi - lo) * frac

    return rate


def spike_profile(base, spike, at_s, dur_s):
    """Constant ``base`` with a rectangular burst to ``spike`` during
    ``[at_s, at_s + dur_s)``."""

    def rate(t):
        return spike if at_s <= t < at_s + dur_s else base

    return rate


def poisson_arrivals(rate_fn, duration_s, rate_max, seed):
    """Arrival offsets in [0, duration_s) by Lewis-Shedler thinning of
    a homogeneous Poisson process at ``rate_max``. Deterministic for a
    fixed seed."""
    rng = random.Random(seed)
    t = 0.0
    out = []
    while True:
        t += rng.expovariate(rate_max)
        if t >= duration_s:
            return out
        if rng.random() * rate_max <= rate_fn(t):
            out.append(t)


class LoadResult:
    """Outcome of one load run; everything the SLO gate needs."""

    def __init__(self, hist, submitted, completed, failed, errors,
                 duration_s):
        self.hist = hist
        self.submitted = submitted
        self.completed = completed
        self.failed = failed
        self.errors = dict(errors)      # error type name -> count
        self.duration_s = duration_s

    @property
    def lost(self):
        """Futures that never resolved — must be zero."""
        return self.submitted - self.completed - self.failed

    @property
    def achieved_req_s(self):
        return self.completed / self.duration_s if self.duration_s else 0.0

    def quantile(self, q):
        return self.hist.quantile(q)

    def as_dict(self):
        return {
            "submitted": self.submitted, "completed": self.completed,
            "failed": self.failed, "lost": self.lost,
            "errors": self.errors, "duration_s": self.duration_s,
            "achieved_req_s": self.achieved_req_s,
            "p50_ms": self.hist.quantile(0.5),
            "p99_ms": self.hist.quantile(0.99),
        }


class LoadGenerator:
    """Drives ``submit_fn(i) -> future`` under a seeded arrival process.

    The future only needs ``add_done_callback``; latency is recorded in
    the callback against the scheduled (open) or issued (closed)
    arrival time on the monotonic clock."""

    def __init__(self, submit_fn, *, seed=0, registry=None,
                 reservoir=65536):
        self._submit = submit_fn
        self._seed = seed
        self.metrics = registry if registry is not None else MetricsRegistry()
        self._hist = self.metrics.histogram(
            "soak_latency_ms", "request latency from scheduled arrival",
            reservoir=reservoir)
        self._m_submitted = self.metrics.counter(
            "soak_submitted_total", "requests issued")
        self._m_completed = self.metrics.counter(
            "soak_completed_total", "requests resolved ok")
        self._m_failed = self.metrics.counter(
            "soak_failed_total", "requests resolved with a typed error")
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._submitted = 0
        self._resolved = 0
        self._failed = 0
        self._errors = {}

    # ---- completion plumbing -------------------------------------------

    def _record(self, fut, t_ref, t0, done_event=None):
        lat_ms = (time.monotonic() - t0 - t_ref) * 1000.0
        err = None
        try:
            err = fut.exception()
        except Exception as e:          # future-likes without exception()
            err = e
        if err is None:
            self._hist.observe(lat_ms)
            self._m_completed.inc()
        else:
            self._m_failed.inc()
        with self._lock:
            self._resolved += 1
            if err is not None:
                self._failed += 1
                name = type(err).__name__
                self._errors[name] = self._errors.get(name, 0) + 1
            self._cv.notify_all()
        if done_event is not None:
            done_event.set()

    def _issue(self, i, t_ref, t0, done_event=None):
        self._m_submitted.inc()
        with self._lock:
            self._submitted += 1
        try:
            fut = self._submit(i)
        except Exception as e:
            # synchronous rejection (admission/breaker) = resolved failure
            self._m_failed.inc()
            with self._lock:
                self._resolved += 1
                self._failed += 1
                name = type(e).__name__
                self._errors[name] = self._errors.get(name, 0) + 1
                self._cv.notify_all()
            if done_event is not None:
                done_event.set()
            return
        fut.add_done_callback(
            lambda f, r=t_ref, z=t0, d=done_event: self._record(f, r, z, d))

    # ---- open loop -----------------------------------------------------

    def run_open(self, rate_fn, duration_s, rate_max, timeout_s=None):
        """Replay a precomputed Poisson schedule; block until every
        issued request resolves."""
        sched = poisson_arrivals(rate_fn, duration_s, rate_max, self._seed)
        t0 = time.monotonic()
        self._soak_arrival_loop(sched, t0)
        elapsed = self._await_quiesce(t0, timeout_s)
        return self._result(elapsed)

    def _soak_arrival_loop(self, sched, t0):
        # hot path under graftcheck's host-sync rule: pacing + submit
        # only — no device fetches, no scalar coercions
        for i, ts in enumerate(sched):
            delay = ts - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            self._issue(i, ts, t0)

    # ---- closed loop ---------------------------------------------------

    def run_closed(self, workers, requests_per_worker, think_s=0.0,
                   timeout_s=None):
        """N workers in submit-wait loops; latency from each submit."""
        t0 = time.monotonic()

        def _worker(w):
            rng = random.Random(self._seed * 7919 + w)
            for k in range(requests_per_worker):
                t_ref = time.monotonic() - t0
                done = threading.Event()
                self._issue(w * requests_per_worker + k, t_ref, t0,
                            done_event=done)
                done.wait(timeout=60.0)  # closed loop: one in flight
                if think_s:
                    time.sleep(rng.uniform(0.0, 2.0 * think_s))

        threads = [threading.Thread(target=_worker, args=(w,),
                                    name=f"loadgen-{w}", daemon=True)
                   for w in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = self._await_quiesce(t0, timeout_s)
        return self._result(elapsed)

    # ---- shared tail ---------------------------------------------------

    def _await_quiesce(self, t0, timeout_s):
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._cv:
            while self._resolved < self._submitted:
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(min(left, 1.0))
                else:
                    self._cv.wait(1.0)
        return time.monotonic() - t0

    def _result(self, elapsed):
        with self._lock:
            submitted = self._submitted
            resolved = self._resolved
            failed = self._failed
            errors = dict(self._errors)
        completed = resolved - failed
        return LoadResult(self._hist, submitted, completed, failed,
                          errors, elapsed)
