"""Queue-driven autoscaler for the serving layer.

Grows and shrinks two capacity levers from observed queue depth and
deadline-miss rate:

- GenerationServer **active decode slots** — the slot pool is baked
  into the compiled program shapes, so scaling changes an *admission
  cap* (``set_active_slots``), never the pool itself; shrinking takes
  effect as slots retire.
- ParallelInference **coalescer workers** — extra coalescer threads on
  the shared submit queue (``set_coalescer_workers``).

Discipline: hysteresis (a breach must persist ``up_ticks`` /
``down_ticks`` consecutive observations) plus a per-target cooldown
after any change, so an oscillating load produces *zero* decisions
instead of flapping. The clock is injectable and ``tick()`` is manual,
so tests drive the whole state machine deterministically; ``start()``
runs the same tick on a background thread for production.

Every decision lands in the registry
(``autoscale_decisions_total{target,action}``,
``autoscale_level{target}``) and in ``decisions`` as a typed record.
"""

from __future__ import annotations

import collections
import threading
import time

from deeplearning4j_tpu.metrics.registry import MetricsRegistry

__all__ = ["Autoscaler", "ScaleDecision", "GenerationSlotsTarget",
           "CoalescerTarget", "FleetTierTarget"]


class ScaleDecision:
    """One autoscaling action (or refusal), fully typed."""

    __slots__ = ("t", "target", "action", "level_from", "level_to",
                 "queue_depth", "miss_rate", "reason")

    def __init__(self, t, target, action, level_from, level_to,
                 queue_depth, miss_rate, reason):
        self.t = t
        self.target = target
        self.action = action            # "scale_up" | "scale_down"
        self.level_from = level_from
        self.level_to = level_to
        self.queue_depth = queue_depth
        self.miss_rate = miss_rate
        self.reason = reason

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"ScaleDecision({self.target}: {self.action} "
                f"{self.level_from}->{self.level_to} depth="
                f"{self.queue_depth} miss={self.miss_rate:.3f})")


class _StatsTarget:
    """Adapter base: derives (queue depth, deadline-miss rate) from a
    server's public ``stats()`` dict via counter deltas between ticks."""

    name = "target"
    depth_key = "pending"

    def __init__(self, server):
        self._srv = server
        self._prev_misses = 0
        self._prev_served = 0

    def observe(self):
        st = self._srv.stats()
        misses = st["expired"]
        served = st["completed"]
        dm = max(0, misses - self._prev_misses)
        ds = max(0, served - self._prev_served)
        self._prev_misses = misses
        self._prev_served = served
        total = dm + ds
        rate = dm / total if total > 0 else 0.0
        return st[self.depth_key], rate


class GenerationSlotsTarget(_StatsTarget):
    """Scales GenerationServer's active-slot admission cap in
    [1, slots]."""

    name = "generation_slots"
    depth_key = "queued"

    @property
    def min_level(self):
        return 1

    @property
    def max_level(self):
        return self._srv.slots

    def get(self):
        return self._srv.active_slot_cap

    def set(self, n):
        self._srv.set_active_slots(n)


class CoalescerTarget(_StatsTarget):
    """Scales ParallelInference's coalescer worker count in
    [1, max_coalescers]."""

    name = "inference_coalescers"
    depth_key = "pending"

    @property
    def min_level(self):
        return 1

    @property
    def max_level(self):
        return self._srv.max_coalescers

    def get(self):
        return self._srv.coalescer_workers

    def set(self, n):
        self._srv.set_coalescer_workers(n)


class FleetTierTarget:
    """Per-tier slot lever over a disaggregated ReplicaFleet: one
    independent Autoscaler target per ``role`` (prefill capacity bounds
    TTFT, decode capacity bounds inter-token latency — they must scale
    separately). Observes aggregate queue depth and deadline-miss rate
    from ``fleet.tier_stats(role)`` counter deltas and moves the tier's
    shared active-slot admission cap via
    ``fleet.set_tier_active_slots(role, n)``."""

    depth_key = "queued"

    def __init__(self, fleet, role, max_slots=None):
        if role not in ("prefill", "decode", "unified", "knn", "generate"):
            raise ValueError(f"unknown tier role {role!r}")
        self._fleet = fleet
        self._role = role
        self.name = f"fleet_{role}_slots"
        self._max_slots = max_slots
        self._prev_misses = 0
        self._prev_served = 0
        self._level = None  # tracked cap survives tier-dark windows

    @property
    def min_level(self):
        return 1

    @property
    def max_level(self):
        if self._max_slots is not None:
            return self._max_slots
        st = self._fleet.tier_stats(self._role)
        reps = st["replicas"]
        if reps == 0:
            return self._level if self._level is not None else 1
        # the cap is per replica server, so the lever's ceiling is the
        # largest per-replica slot pool in the tier
        return max(1, st["slots"] // reps)

    def observe(self):
        st = self._fleet.tier_stats(self._role)
        misses = st["expired"]
        served = st["completed"]
        dm = max(0, misses - self._prev_misses)
        ds = max(0, served - self._prev_served)
        self._prev_misses = misses
        self._prev_served = served
        total = dm + ds
        rate = dm / total if total > 0 else 0.0
        return st[self.depth_key], rate

    def get(self):
        st = self._fleet.tier_stats(self._role)
        if st["replicas"] == 0:  # tier dark: hold the last known level
            return self._level if self._level is not None else 1
        level = max(1, st["active_slots"] // st["replicas"])
        self._level = level
        return level

    def set(self, n):
        self._level = n
        self._fleet.set_tier_active_slots(self._role, n)


class Autoscaler:
    """Hysteresis + cooldown controller over one or more targets.

    Scale up when queue depth > ``high_depth`` or miss rate >
    ``high_miss_rate`` for ``up_ticks`` consecutive ticks; scale down
    when depth < ``low_depth`` and miss rate ~ 0 for ``down_ticks``
    consecutive ticks. ``cooldown_s`` quarantines a target after any
    change. One step per decision."""

    def __init__(self, targets, *, high_depth=8, low_depth=1,
                 high_miss_rate=0.05, up_ticks=2, down_ticks=5,
                 cooldown_s=5.0, registry=None, clock=time.monotonic):
        self.targets = list(targets)
        self.high_depth = high_depth
        self.low_depth = low_depth
        self.high_miss_rate = high_miss_rate
        self.up_ticks = up_ticks
        self.down_ticks = down_ticks
        self.cooldown_s = cooldown_s
        self.decisions = collections.deque(maxlen=256)
        self._clock = clock
        self._state = {t.name: {"hi": 0, "lo": 0, "last_change": None}
                       for t in self.targets}
        self._thread = None
        self._stop = threading.Event()
        self.metrics = registry if registry is not None else MetricsRegistry()
        self._m_decisions = self.metrics.counter(
            "autoscale_decisions_total", "autoscaler actions taken",
            labels=("target", "action"))
        self._m_ticks = self.metrics.counter(
            "autoscale_ticks_total", "autoscaler evaluation passes")
        self._m_level = self.metrics.gauge(
            "autoscale_level", "current capacity level", labels=("target",))
        self._m_depth = self.metrics.gauge(
            "autoscale_queue_depth", "last observed queue depth",
            labels=("target",))
        self._m_miss = self.metrics.gauge(
            "autoscale_miss_rate", "last observed deadline-miss rate",
            labels=("target",))

    # ---- the control loop ----------------------------------------------

    def tick(self):
        """Evaluate every target once; returns the decisions made."""
        return self._autoscale_tick()

    def _autoscale_tick(self):
        # hot path under graftcheck's host-sync rule: observations are
        # already host scalars, no coercions or device fetches here
        now = self._clock()
        self._m_ticks.inc()
        made = []
        for target in self.targets:
            depth, miss = target.observe()
            st = self._state[target.name]
            self._m_depth.labels(target=target.name).set(depth)
            self._m_miss.labels(target=target.name).set(miss)
            hot = depth > self.high_depth or miss > self.high_miss_rate
            cold = depth < self.low_depth and miss <= 0.0
            st["hi"] = st["hi"] + 1 if hot else 0
            st["lo"] = st["lo"] + 1 if cold else 0
            level = target.get()
            self._m_level.labels(target=target.name).set(level)
            last = st["last_change"]
            if last is not None and now - last < self.cooldown_s:
                continue
            if st["hi"] >= self.up_ticks and level < target.max_level:
                self._apply(target, st, now, level, level + 1, "scale_up",
                            depth, miss,
                            f"depth={depth} miss={miss:.3f} for "
                            f"{st['hi']} ticks")
                made.append(self.decisions[-1])
            elif st["lo"] >= self.down_ticks and level > target.min_level:
                self._apply(target, st, now, level, level - 1, "scale_down",
                            depth, miss,
                            f"idle for {st['lo']} ticks")
                made.append(self.decisions[-1])
        return made

    def _apply(self, target, st, now, level, new_level, action, depth,
               miss, reason):
        target.set(new_level)
        st["last_change"] = now
        st["hi"] = 0
        st["lo"] = 0
        self._m_decisions.labels(target=target.name, action=action).inc()
        self._m_level.labels(target=target.name).set(new_level)
        self.decisions.append(ScaleDecision(
            t=now, target=target.name, action=action, level_from=level,
            level_to=new_level, queue_depth=depth, miss_rate=miss,
            reason=reason))

    # ---- background operation ------------------------------------------

    def start(self, interval_s=1.0):
        if self._thread is not None:
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(interval_s):
                self.tick()

        self._thread = threading.Thread(target=_run, name="autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def stats(self):
        return {
            "targets": {t.name: t.get() for t in self.targets},
            "decisions": [d.as_dict() for d in self.decisions],
        }
