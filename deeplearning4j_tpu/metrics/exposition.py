"""Prometheus text exposition (format 0.0.4) over one or more
registries.

``render_text(sources)`` takes ``[(inject_labels, registry), ...]`` and
merges same-named families across sources into a single ``# HELP`` /
``# TYPE`` block — KerasBackendServer scrapes its own registry plus one
registry per attached model (injected ``{model="m0", kind="infer"}``),
any extra registrations (broker), and the global registry (health
guard, StatsListener), all on one ``GET /metrics`` page.

Histograms render the bucket/sum/count triple only; reservoir
quantiles live in the JSON snapshot (mixing summary-style quantile
samples into a histogram family is invalid exposition).
"""

from __future__ import annotations

import math

__all__ = ["render_text", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v):
    """Prometheus sample value: integral floats as bare ints."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s):
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s):
    return (str(s).replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


def _labelstr(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels.items())
    return "{" + inner + "}"


def _le(upper):
    return "+Inf" if math.isinf(upper) else _fmt(upper)


def render_text(sources):
    """``sources``: iterable of ``(inject_labels, registry)``. Injected
    labels are prepended to every sample of that registry; collisions
    resolve in favor of the sample's own label. A source's second
    element may also be a pre-collected families list (the
    ``_snapshot_families()`` shape, possibly round-tripped through
    JSON) — the federation router exposes each remote host's last
    stats-gossip families this way, so one scrape of the router shows
    every host without a live socket per scrape."""
    merged = {}   # name -> {"help":, "kind":, "samples": [(labels, data)]}
    order = []
    for inject, reg in sources:
        inject = dict(inject or {})
        fams = (reg._snapshot_families()
                if hasattr(reg, "_snapshot_families") else reg)
        for fam in fams:
            slot = merged.get(fam["name"])
            if slot is None:
                slot = {"help": fam["help"], "kind": fam["kind"],
                        "samples": []}
                merged[fam["name"]] = slot
                order.append(fam["name"])
            elif slot["kind"] != fam["kind"]:
                # kind clash across sources: keep the first, drop the rest
                continue
            if not slot["help"] and fam["help"]:
                slot["help"] = fam["help"]
            for lbls, data in fam["samples"]:
                full = dict(inject)
                full.update(lbls)
                slot["samples"].append((full, data))

    lines = []
    for name in order:
        fam = merged[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['kind']}")
        for labels, data in fam["samples"]:
            if fam["kind"] == "histogram":
                for upper, cum in data["buckets"]:
                    blabels = dict(labels)
                    blabels["le"] = _le(upper)
                    lines.append(
                        f"{name}_bucket{_labelstr(blabels)} {_fmt(cum)}")
                lines.append(
                    f"{name}_sum{_labelstr(labels)} {_fmt(data['sum'])}")
                lines.append(
                    f"{name}_count{_labelstr(labels)} {_fmt(data['count'])}")
            else:
                lines.append(f"{name}{_labelstr(labels)} {_fmt(data)}")
    return "\n".join(lines) + "\n" if lines else ""
