"""Unified observability layer: metrics registry, Prometheus
exposition, queue-driven autoscaling, and the seeded load harness.

The reference's layer 6 (StatsListener -> StatsStorage -> Play server)
rebuilt for a traced + threaded serving stack: every serving and
training surface publishes through one :class:`MetricsRegistry`, the
HTTP server renders it as Prometheus text at ``GET /metrics``, and the
legacy ``/stats`` JSON is re-derived from the same counters.
"""

from deeplearning4j_tpu.metrics.registry import (           # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, NullRegistry,
    DEFAULT_BUCKETS, DEFAULT_QUANTILES, global_registry, nearest_rank,
)
from deeplearning4j_tpu.metrics.exposition import (         # noqa: F401
    render_text, CONTENT_TYPE,
)
from deeplearning4j_tpu.metrics.autoscale import (          # noqa: F401
    Autoscaler, ScaleDecision, GenerationSlotsTarget, CoalescerTarget,
)
from deeplearning4j_tpu.metrics.loadgen import (            # noqa: F401
    LoadGenerator, LoadResult, ramp_profile, spike_profile,
    poisson_arrivals,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NullRegistry",
    "DEFAULT_BUCKETS", "DEFAULT_QUANTILES", "global_registry",
    "nearest_rank", "render_text", "CONTENT_TYPE", "Autoscaler",
    "ScaleDecision", "GenerationSlotsTarget", "CoalescerTarget",
    "LoadGenerator", "LoadResult", "ramp_profile", "spike_profile",
    "poisson_arrivals",
]
