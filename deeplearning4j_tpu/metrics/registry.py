"""Thread-safe metrics registry: counters, gauges, histograms.

The single publication path for every serving and training surface
(reference layer 6: StatsListener -> StatsStorage -> Play server,
rebuilt as a Prometheus-shaped registry). Three metric kinds:

- :class:`Counter` — monotone float, batched ``inc(n)``.
- :class:`Gauge` — settable level, or a *callback* gauge whose value is
  read lazily at collect time (``fn=``) so hot paths never write it.
- :class:`Histogram` — fixed cumulative buckets (Prometheus
  ``_bucket{le=...}`` semantics) plus a seeded reservoir (algorithm R)
  for p50/p90/p99/p999 nearest-rank quantiles, and a monotonic-clock
  ``timer()`` context manager.

Every metric guards its state with its own leaf lock, so instrumented
code never holds a serving lock (``_cond`` / ``_lock``) to publish —
that is what lets the re-homed ``stats()`` methods assemble their
snapshots *outside* the serving locks (fleet.py's pattern, now
enforced). Instrumentation stays out of compiled code: registry writes
happen only at host boundaries (done-callbacks, retire paths, loop
edges) — the graftcheck host-sync rule audits ``_snapshot_families``
like any other hot loop.

Metric names follow Prometheus conventions (``*_total`` counters,
unit-suffixed histograms). Families support label sets::

    reg = MetricsRegistry()
    c = reg.counter("requests_total", "served requests", labels=("code",))
    c.labels(code="200").inc()
    h = reg.histogram("latency_ms", "e2e latency")
    with h.timer():
        serve()
    h.quantile(0.99)

``NullRegistry`` is the same API with every operation a no-op — the
two-leg ``metrics_overhead`` bench swaps it in to price the real one.
"""

from __future__ import annotations

import bisect
import math
import random
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NullRegistry",
    "DEFAULT_BUCKETS", "DEFAULT_QUANTILES", "global_registry",
]

# latency-in-ms oriented default buckets; +Inf is implicit
DEFAULT_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                   250.0, 500.0, 1000.0, 2500.0, 5000.0)
DEFAULT_QUANTILES = (0.5, 0.9, 0.99, 0.999)
DEFAULT_RESERVOIR = 1024


def nearest_rank(sorted_xs, q):
    """Nearest-rank quantile on a sorted sequence: the canonical
    ``max(0, ceil(q*n) - 1)`` index (bench.py's old
    ``int(len(xs) * q)`` overshoots by one at small N)."""
    n = len(sorted_xs)
    if n == 0:
        return float("nan")
    idx = max(0, math.ceil(q * n) - 1)
    return sorted_xs[min(idx, n - 1)]


class _Timer:
    """Context manager observing elapsed milliseconds on a histogram.
    Monotonic clock: timers measure durations, never wall-clock."""

    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._hist.observe((time.monotonic() - self._t0) * 1000.0)
        return False


class Counter:
    """Monotone counter. ``inc(n)`` supports batched adds (generation's
    per-dispatch counter updates land as one locked add)."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, n=1.0):
        if n < 0:
            raise ValueError("counter can only increase")
        with self._lock:
            self._v += n

    @property
    def value(self):
        with self._lock:
            return self._v


class Gauge:
    """Settable level, or a callback gauge (``fn=``) evaluated at
    collect time — admission pending, breaker state, page-pool
    occupancy surface without any hot-path write."""

    __slots__ = ("_lock", "_v", "_fn")

    def __init__(self, fn=None):
        self._lock = threading.Lock()
        self._v = 0.0
        self._fn = fn

    def set(self, v):
        with self._lock:
            self._v = v

    def inc(self, n=1.0):
        with self._lock:
            self._v += n

    def dec(self, n=1.0):
        with self._lock:
            self._v -= n

    @property
    def value(self):
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._v


class Histogram:
    """Fixed cumulative buckets + seeded reservoir quantiles.

    Buckets carry Prometheus semantics: ``_bucket{le=b}`` is the count
    of observations ``<= b`` (cumulative at snapshot time), ``+Inf``
    implicit. The reservoir is algorithm R over a per-histogram
    ``random.Random(seed)`` — string-seeded, so quantiles are
    deterministic across runs regardless of ``PYTHONHASHSEED``. With
    ``reservoir >= n`` observations the quantiles are exact
    nearest-rank; beyond that they degrade gracefully to a uniform
    sample."""

    __slots__ = ("_lock", "_uppers", "_counts", "_sum", "_n",
                 "_res", "_res_cap", "_rng")

    def __init__(self, buckets=DEFAULT_BUCKETS, reservoir=DEFAULT_RESERVOIR,
                 seed="histogram"):
        self._lock = threading.Lock()
        self._uppers = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self._uppers) + 1)  # last = +Inf
        self._sum = 0.0
        self._n = 0
        self._res = []
        self._res_cap = int(reservoir)
        self._rng = random.Random(seed)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._n += 1
            self._sum += v
            self._counts[bisect.bisect_left(self._uppers, v)] += 1
            if len(self._res) < self._res_cap:
                self._res.append(v)
            else:
                j = self._rng.randrange(self._n)
                if j < self._res_cap:
                    self._res[j] = v

    def timer(self):
        return _Timer(self)

    @property
    def count(self):
        with self._lock:
            return self._n

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def quantile(self, q):
        with self._lock:
            xs = sorted(self._res)
        return nearest_rank(xs, q)

    def quantiles(self, qs=DEFAULT_QUANTILES):
        with self._lock:
            xs = sorted(self._res)
        return {q: nearest_rank(xs, q) for q in qs}

    def _snapshot(self):
        with self._lock:
            counts = list(self._counts)
            total = self._n
            s = self._sum
            xs = sorted(self._res)
        cum = 0
        buckets = []
        for upper, c in zip(self._uppers, counts):
            cum += c
            buckets.append((upper, cum))
        buckets.append((math.inf, total))
        return {
            "buckets": buckets, "sum": s, "count": total,
            "quantiles": {q: nearest_rank(xs, q) for q in DEFAULT_QUANTILES},
        }


class _Family:
    """One named metric family; children keyed by label values. With no
    label names the family has a single anonymous child and proxies the
    metric API (``inc``/``set``/``observe``/...) straight to it."""

    kind = "untyped"

    def __init__(self, name, help_text, label_names, maker):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._maker = maker
        self._lock = threading.Lock()
        self._children = {}

    def labels(self, **kv):
        key = tuple(str(kv[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._maker()
                self._children[key] = child
            return child

    def samples(self):
        """[(labels_dict, metric)] — labels_dict ordered as declared."""
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.label_names, key)), m) for key, m in items]

    def _default(self):
        if self.label_names:
            raise ValueError(
                f"metric {self.name!r} has labels {self.label_names}; "
                "use .labels(...)")
        return self.labels()


class CounterFamily(_Family):
    kind = "counter"

    def inc(self, n=1.0):
        self._default().inc(n)

    @property
    def value(self):
        return self._default().value


class GaugeFamily(_Family):
    kind = "gauge"

    def set(self, v):
        self._default().set(v)

    def inc(self, n=1.0):
        self._default().inc(n)

    def dec(self, n=1.0):
        self._default().dec(n)

    @property
    def value(self):
        return self._default().value


class HistogramFamily(_Family):
    kind = "histogram"

    def observe(self, v):
        self._default().observe(v)

    def timer(self):
        return self._default().timer()

    def quantile(self, q):
        return self._default().quantile(q)

    def quantiles(self, qs=DEFAULT_QUANTILES):
        return self._default().quantiles(qs)

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum


class MetricsRegistry:
    """Get-or-create metric families by name; snapshots for exposition.

    ``counter``/``gauge``/``histogram`` are idempotent: the same name
    returns the same family (a kind clash raises). Collection never
    blocks publication for long: ``_snapshot_families`` lists the
    families under the registry lock, then drains each family's leaf
    lock one at a time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}

    # ---- registration --------------------------------------------------

    def counter(self, name, help_text="", labels=()):
        return self._family(name, help_text, labels,
                            CounterFamily, Counter)

    def gauge(self, name, help_text="", labels=(), fn=None):
        return self._family(name, help_text, labels,
                            GaugeFamily, lambda: Gauge(fn=fn))

    def histogram(self, name, help_text="", labels=(),
                  buckets=DEFAULT_BUCKETS, reservoir=DEFAULT_RESERVOIR):
        return self._family(
            name, help_text, labels, HistogramFamily,
            lambda: Histogram(buckets=buckets, reservoir=reservoir,
                              seed=name))

    def _family(self, name, help_text, labels, fam_cls, maker):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = fam_cls(name, help_text, labels, maker)
                self._families[name] = fam
        if not isinstance(fam, fam_cls):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}")
        if tuple(labels) != fam.label_names:
            raise ValueError(
                f"metric {name!r} label set {fam.label_names} != "
                f"{tuple(labels)}")
        if not fam.label_names:
            fam.labels()  # eager default child: exposes 0 before first use
        return fam

    # ---- collection ----------------------------------------------------

    def _snapshot_families(self):
        """Collect every family into plain host data. Registered in
        graftcheck HOT_FUNCTIONS: no device fetches, no float()/int()
        coercions — values are already host floats when they get here."""
        with self._lock:
            fams = list(self._families.values())
        out = []
        for fam in fams:
            samples = []
            for lbls, metric in fam.samples():
                if fam.kind == "histogram":
                    samples.append((lbls, metric._snapshot()))
                else:
                    samples.append((lbls, metric.value))
            out.append({"name": fam.name, "help": fam.help,
                        "kind": fam.kind, "samples": samples})
        return out

    def snapshot(self):
        """JSON-friendly snapshot: {name: value | {labels...} | hist}."""
        out = {}
        for fam in self._snapshot_families():
            if fam["kind"] == "histogram":
                val = {("|".join(f"{k}={v}" for k, v in lbls.items())
                        if lbls else ""): data
                       for lbls, data in fam["samples"]}
                out[fam["name"]] = val.get("", val)
            elif any(lbls for lbls, _ in fam["samples"]):
                out[fam["name"]] = {
                    "|".join(f"{k}={v}" for k, v in lbls.items()): v2
                    for lbls, v2 in fam["samples"]}
            else:
                out[fam["name"]] = (fam["samples"][0][1]
                                    if fam["samples"] else 0.0)
        return out


class _NullMetric:
    """Accepts the whole metric API and does nothing."""

    def inc(self, n=1.0):
        pass

    def dec(self, n=1.0):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    def timer(self):
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def labels(self, **kv):
        return self

    def quantile(self, q):
        return float("nan")

    def quantiles(self, qs=DEFAULT_QUANTILES):
        return {q: float("nan") for q in qs}

    @property
    def value(self):
        return 0.0

    @property
    def count(self):
        return 0

    @property
    def sum(self):
        return 0.0


_NULL_METRIC = _NullMetric()


class NullRegistry:
    """Same API as :class:`MetricsRegistry`, every operation a no-op —
    the control leg of the metrics-overhead gate."""

    def counter(self, name, help_text="", labels=()):
        return _NULL_METRIC

    def gauge(self, name, help_text="", labels=(), fn=None):
        return _NULL_METRIC

    def histogram(self, name, help_text="", labels=(),
                  buckets=DEFAULT_BUCKETS, reservoir=DEFAULT_RESERVOIR):
        return _NULL_METRIC

    def _snapshot_families(self):
        return []

    def snapshot(self):
        return {}


_GLOBAL = MetricsRegistry()


def global_registry():
    """The process-wide default registry. Training-side surfaces (the
    health guard, StatsListener) publish here so a serving process and
    its training loop share one scrape."""
    return _GLOBAL
