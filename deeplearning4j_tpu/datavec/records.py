"""Record readers (reference: DataVec's RecordReader implementations —
CSVRecordReader, CSVSequenceRecordReader, ImageRecordReader; the SPI the
deeplearning4j-core bridge iterators consume).

A record is a list of values (floats/strings); a sequence record is a list of
records. Readers are restartable iterables.
"""

from __future__ import annotations

import csv
import os
from typing import Iterable, Optional


class RecordReader:
    def __iter__(self):
        self.reset()
        return self._gen()

    def _gen(self):
        raise NotImplementedError

    def reset(self) -> None:
        pass


class SequenceRecordReader(RecordReader):
    pass


class CollectionRecordReader(RecordReader):
    """In-memory records (reference: CollectionRecordReader)."""

    def __init__(self, records: Iterable[list]):
        self.records = [list(r) for r in records]

    def _gen(self):
        yield from self.records


class CollectionSequenceRecordReader(SequenceRecordReader):
    def __init__(self, sequences: Iterable[list]):
        self.sequences = [[list(r) for r in seq] for seq in sequences]

    def _gen(self):
        yield from self.sequences


class CSVRecordReader(RecordReader):
    """CSV rows -> records (reference: CSVRecordReader — skip lines +
    delimiter).

    Purely numeric files take the native C parser (datavec is the
    framework's data loader; its hot path is native, matching the
    reference's native-backed ingestion — see
    deeplearning4j_tpu/native/fastio.c); anything the fast path cannot
    represent (string fields, ragged rows) falls back to the Python csv
    module transparently."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def read_numeric(self):
        """Whole-file bulk parse -> float64 [rows, cols] ndarray, or None
        when the file is not purely numeric (or no native lib)."""
        from deeplearning4j_tpu.native import parse_numeric_csv
        return parse_numeric_csv(self.path, self.delimiter, self.skip_lines)

    def _gen(self):
        arr = self.read_numeric()
        if arr is not None:
            # tolist() converts to builtin floats in one C pass (~4x less
            # overhead than per-element float() over numpy scalars)
            yield from arr.tolist()
            return
        with open(self.path, newline="", encoding="utf-8") as f:
            reader = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield [_maybe_float(v) for v in row]


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (reference: CSVSequenceRecordReader over a
    file split). ``paths`` may be a directory (sorted files) or a list."""

    def __init__(self, paths, skip_lines: int = 0, delimiter: str = ","):
        if isinstance(paths, str):
            self.paths = [os.path.join(paths, f)
                          for f in sorted(os.listdir(paths))]
        else:
            self.paths = list(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def _gen(self):
        for p in self.paths:
            seq = list(CSVRecordReader(p, self.skip_lines, self.delimiter))
            if seq:
                yield seq


class ImageRecordReader(RecordReader):
    """Image files -> [h, w, c] float arrays + label from parent directory
    (reference: ImageRecordReader + ParentPathLabelGenerator). NHWC, scaled
    to [0, 1]."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 root: Optional[str] = None, paths: Optional[list] = None,
                 labels: Optional[list] = None):
        self.height = height
        self.width = width
        self.channels = channels
        if root is not None:
            self.paths = []
            for d in sorted(os.listdir(root)):
                full = os.path.join(root, d)
                if os.path.isdir(full):
                    for f in sorted(os.listdir(full)):
                        self.paths.append((os.path.join(full, f), d))
            self.labels = sorted({lab for _, lab in self.paths})
        else:
            self.paths = [(p, lab) for p, lab in zip(paths, labels)]
            self.labels = sorted(set(labels))
        self._label_idx = {l: i for i, l in enumerate(self.labels)}

    def num_labels(self) -> int:
        return len(self.labels)

    def _gen(self):
        import numpy as np
        from PIL import Image

        for path, lab in self.paths:
            img = Image.open(path)
            img = img.convert("RGB" if self.channels == 3 else "L")
            img = img.resize((self.width, self.height))
            arr = np.asarray(img, np.float32) / 255.0
            if arr.ndim == 2:
                arr = arr[:, :, None]
            yield [arr, self._label_idx[lab]]


def _maybe_float(v: str):
    try:
        return float(v)
    except (TypeError, ValueError):
        return v
