"""DataVec bridge: record readers -> DataSet minibatches.

Reference: the external DataVec library's RecordReader SPI plus
deeplearning4j-core's bridge iterators
(datasets/datavec/RecordReaderDataSetIterator.java,
SequenceRecordReaderDataSetIterator.java, RecordReaderMultiDataSetIterator).
"""

from deeplearning4j_tpu.datavec.records import (
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
)
from deeplearning4j_tpu.datavec.iterators import (
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)

__all__ = [
    "CSVRecordReader", "CSVSequenceRecordReader", "CollectionRecordReader",
    "CollectionSequenceRecordReader", "ImageRecordReader",
    "RecordReaderDataSetIterator", "SequenceRecordReaderDataSetIterator",
    "RecordReaderMultiDataSetIterator",
]
