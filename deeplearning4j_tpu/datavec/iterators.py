"""Record-reader -> DataSet bridge iterators.

Reference: deeplearning4j-core datasets/datavec/
RecordReaderDataSetIterator.java (label column -> one-hot or regression
target), SequenceRecordReaderDataSetIterator.java (per-timestep labels,
ALIGN_END masking for variable length), RecordReaderMultiDataSetIterator
(named-reader column selections -> MultiDataSet).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet


class RecordReaderDataSetIterator:
    """records -> DataSet batches (reference:
    RecordReaderDataSetIterator.java — labelIndex/numPossibleLabels for
    classification, regression flag for raw targets)."""

    def __init__(self, reader, batch_size: int, label_index: int = -1,
                 num_classes: Optional[int] = None, regression: bool = False,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to

    def reset(self):
        pass

    def __iter__(self):
        feats, labs = [], []
        for rec in self.reader:
            f, l = self._split(rec)
            feats.append(f)
            labs.append(l)
            if len(feats) == self.batch_size:
                yield self._make(feats, labs)
                feats, labs = [], []
        if feats:
            yield self._make(feats, labs)

    def _split(self, rec):
        if isinstance(rec[0], np.ndarray):  # image record: [array, label]
            return rec[0], rec[1]
        li = self.label_index if self.label_index >= 0 else len(rec) - 1
        if self.label_index_to is not None:  # multi-column regression target
            lab = [float(v) for v in rec[li:self.label_index_to + 1]]
            feat = [float(v) for i, v in enumerate(rec)
                    if i < li or i > self.label_index_to]
        else:
            lab = rec[li]
            feat = [float(v) for i, v in enumerate(rec) if i != li]
        return feat, lab

    def _make(self, feats, labs):
        x = np.asarray(feats, np.float32)
        if self.regression:
            y = np.asarray(labs, np.float32)
            if y.ndim == 1:
                y = y[:, None]
        else:
            n = self.num_classes or int(max(float(l) for l in labs)) + 1
            y = np.eye(n, dtype=np.float32)[
                np.asarray([int(float(l)) for l in labs])]
        return DataSet(x, y)


class SequenceRecordReaderDataSetIterator:
    """sequence records -> padded+masked rnn DataSets (reference:
    SequenceRecordReaderDataSetIterator.java, AlignmentMode.ALIGN_END
    semantics collapsed to: pad to batch max length, mask marks valid
    steps)."""

    def __init__(self, reader, batch_size: int, label_index: int = -1,
                 num_classes: Optional[int] = None, regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression

    def reset(self):
        pass

    def __iter__(self):
        buf = []
        for seq in self.reader:
            buf.append(seq)
            if len(buf) == self.batch_size:
                yield self._make(buf)
                buf = []
        if buf:
            yield self._make(buf)

    def _make(self, seqs):
        B = len(seqs)
        T = max(len(s) for s in seqs)
        li = self.label_index if self.label_index >= 0 \
            else len(seqs[0][0]) - 1
        F = len(seqs[0][0]) - 1
        x = np.zeros((B, T, F), np.float32)
        mask = np.zeros((B, T), np.float32)
        raw_labels = np.zeros((B, T), np.float32)
        for b, s in enumerate(seqs):
            for t, rec in enumerate(s):
                x[b, t] = [float(v) for i, v in enumerate(rec) if i != li]
                raw_labels[b, t] = float(rec[li])
                mask[b, t] = 1.0
        if self.regression:
            y = raw_labels[..., None]
        else:
            n = self.num_classes or int(raw_labels.max()) + 1
            y = np.eye(n, dtype=np.float32)[raw_labels.astype(int)]
            y *= mask[..., None]
        return DataSet(x, y, features_mask=mask, labels_mask=mask)


class RecordReaderMultiDataSetIterator:
    """Named readers + input/output column selections -> MultiDataSet
    (reference: RecordReaderMultiDataSetIterator.Builder addInput/
    addOutputOneHot)."""

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self._readers: dict = {}
        self._inputs: list = []   # (reader_name, col_from, col_to)
        self._outputs: list = []  # (reader_name, col, num_classes|None)

    def add_reader(self, name: str, reader):
        self._readers[name] = reader
        return self

    def add_input(self, reader_name: str, col_from: int, col_to: int):
        self._inputs.append((reader_name, col_from, col_to))
        return self

    def add_output_one_hot(self, reader_name: str, col: int,
                           num_classes: int):
        self._outputs.append((reader_name, col, num_classes))
        return self

    def add_output(self, reader_name: str, col_from: int, col_to: int):
        self._outputs.append((reader_name, (col_from, col_to), None))
        return self

    def reset(self):
        pass

    def __iter__(self):
        iters = {k: iter(r) for k, r in self._readers.items()}
        while True:
            rows = {}
            try:
                batch = [{k: next(it) for k, it in iters.items()}
                         for _ in range(self.batch_size)]
            except StopIteration:
                return
            rows = batch
            feats = []
            for name, c0, c1 in self._inputs:
                feats.append(np.asarray(
                    [[float(v) for v in r[name][c0:c1 + 1]] for r in rows],
                    np.float32))
            labs = []
            for name, col, n in self._outputs:
                if n is not None:
                    idx = [int(float(r[name][col])) for r in rows]
                    labs.append(np.eye(n, dtype=np.float32)[idx])
                else:
                    c0, c1 = col
                    labs.append(np.asarray(
                        [[float(v) for v in r[name][c0:c1 + 1]]
                         for r in rows], np.float32))
            yield MultiDataSet(feats, labs)
