"""Cross-process streaming ingestion (the dl4j-streaming analog).

Reference: dl4j-streaming's Kafka/Camel stack — routes
(streaming/routes/CamelKafkaRouteBuilder.java:16), publisher
(streaming/kafka/NDArrayPublisher.java), consumer
(streaming/kafka/NDArrayConsumer.java), serde
(serde/RecordSerializer.java). No Kafka broker exists in this
environment, so the broker itself is part of the framework: a small
TCP pub/sub topic broker with length-prefixed binary NDArray frames.
The pieces compose the same way the reference's do:

    producer process:  NDArrayPublisher -> (tcp) -> StreamingBroker
    trainer process:   StreamingBroker -> NDArrayConsumer ->
                       QueueDataSetIterator -> net.fit(...)

``NDArrayRoute`` is the Camel-route analog: one call wires a consumer
subscription into a queue iterator on a background thread.
"""

from deeplearning4j_tpu.streaming.broker import StreamingBroker
from deeplearning4j_tpu.streaming.client import (
    NDArrayConsumer,
    NDArrayPublisher,
    NDArrayRoute,
    StreamStalled,
)
from deeplearning4j_tpu.streaming.serde import (
    dataset_from_bytes,
    dataset_to_bytes,
)

__all__ = [
    "StreamingBroker",
    "NDArrayPublisher",
    "NDArrayConsumer",
    "NDArrayRoute",
    "StreamStalled",
    "dataset_to_bytes",
    "dataset_from_bytes",
]
