"""DataSet <-> bytes for the streaming wire (reference:
dl4j-streaming serde/RecordSerializer.java + kafka NDArray message
payloads). npz container: self-describing shapes/dtypes, no pickle —
a frame from an untrusted producer can only decode into arrays."""

from __future__ import annotations

import io

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

_FIELDS = ("features", "labels", "features_mask", "labels_mask")


def dataset_to_bytes(ds: DataSet) -> bytes:
    arrays = {}
    for name in _FIELDS:
        v = getattr(ds, name, None)
        if v is not None:
            arrays[name] = np.asarray(v)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def dataset_from_bytes(payload: bytes) -> DataSet:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        kw = {name: z[name] for name in _FIELDS if name in z.files}
    return DataSet(kw.get("features"), kw.get("labels"),
                   features_mask=kw.get("features_mask"),
                   labels_mask=kw.get("labels_mask"))
