"""TCP pub/sub topic broker for NDArray streams.

Reference semantics: the Kafka broker in dl4j-streaming's routes
(CamelKafkaRouteBuilder.java:16 wires record publishers to topic
consumers). This is the minimal broker that gives the same contract on
one machine or a LAN: named topics, many publishers, many subscribers
(every subscriber sees every frame — Kafka consumer-group-per-subscriber
semantics), bounded per-subscriber buffering with publisher backpressure,
and an explicit end-of-stream marker.

Wire protocol (all big-endian):
    frame   = op(1) topic_len(2) topic payload_len(4) payload
    ops     : P publish data | E end-of-topic | S subscribe (payload "")
            | K subscribe-ack (broker -> subscriber, payload "")
A subscriber sends S and MUST read the K ack before treating the
connection as live; after the ack it receives the publisher's P/E frames
verbatim for its topic, with no frame published after the ack missed.

Run standalone: ``python -m deeplearning4j_tpu.streaming.broker --port N``
or embedded: ``StreamingBroker(port=0).start()``.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import Optional

from deeplearning4j_tpu.metrics.registry import MetricsRegistry

_HDR = struct.Struct(">cH")
_LEN = struct.Struct(">I")

OP_PUBLISH = b"P"
OP_END = b"E"
OP_SUBSCRIBE = b"S"
OP_SUB_ACK = b"K"

MAX_FRAME_BYTES = 1 << 30  # default defensive bound on payload_len


class FrameTooLarge(ValueError):
    """A frame's length prefix declared a payload above the reader's
    ``max_frame_bytes`` cap. A corrupt (or hostile) 4-byte length must
    be rejected typed BEFORE any allocation is attempted — trusting it
    turns one flipped bit into an unbounded ``recv`` buffer. Subclasses
    ``ValueError`` so pre-existing ``except (OSError, ValueError)``
    connection handlers keep dropping the poisoned connection."""


def read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(sock: socket.socket,
               max_frame_bytes: int = MAX_FRAME_BYTES):
    """(op, topic, payload) or None on clean EOF. A length prefix
    above ``max_frame_bytes`` fails typed ``FrameTooLarge`` — never an
    attempted allocation of attacker/corruption-controlled size."""
    hdr = read_exact(sock, _HDR.size)
    if hdr is None:
        return None
    op, tlen = _HDR.unpack(hdr)
    topic = read_exact(sock, tlen)
    if topic is None:
        return None
    raw = read_exact(sock, _LEN.size)
    if raw is None:
        return None
    (plen,) = _LEN.unpack(raw)
    if plen > max_frame_bytes:
        raise FrameTooLarge(f"frame of {plen} bytes exceeds the "
                            f"{max_frame_bytes}-byte bound")
    payload = read_exact(sock, plen) if plen else b""
    if payload is None:
        return None
    return op, topic.decode("utf-8"), payload


def write_frame(sock: socket.socket, op: bytes, topic: str,
                payload: bytes = b"") -> None:
    t = topic.encode("utf-8")
    sock.sendall(_HDR.pack(op, len(t)) + t + _LEN.pack(len(payload))
                 + payload)


# imported AFTER the wire-protocol surface: pulling in the parallel
# package re-enters this module through streaming.client (resilience
# re-exports StreamStalled), which only needs the OP_* constants and
# frame helpers above
from deeplearning4j_tpu.parallel.runtime import (EXIT,  # noqa: E402
                                                 ServingLoop, supervisor)


class _Subscriber:
    def __init__(self, sock: socket.socket, topic: str, maxsize: int):
        self.sock = sock
        self.topic = topic
        self.q: queue.Queue = queue.Queue(maxsize=maxsize)
        self.loop: Optional[ServingLoop] = None  # writer (set pre-register)
        self.alive = True
        self.dropped = 0            # frames this subscriber never received
        self.consecutive_drops = 0  # resets on every delivered frame


class StreamingBroker:
    """Threaded topic broker. ``port=0`` picks a free port (see
    ``.port``). One writer thread per subscriber drains its bounded
    queue; a publish backpressures (blocks up to ``publish_patience_s``)
    while a live subscriber's queue is full — a slow consumer throttles
    the stream instead of exhausting broker memory, the same role Kafka's
    bounded log + consumer lag plays for the reference.

    A subscriber that stays full PAST the patience window no longer stalls
    every other subscriber silently: the frame is dropped *for that
    subscriber only*, counted (``stats()``), and after ``drop_limit``
    CONSECUTIVE drops the subscriber is disconnected (it can reconnect and
    resubscribe) — the Kafka consumer-eviction analog. Set
    ``publish_patience_s=None`` for the legacy block-forever backpressure
    (no drops, no eviction)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 subscriber_buffer: int = 16, drop_limit: int = 8,
                 publish_patience_s: Optional[float] = 0.5,
                 registry: Optional[MetricsRegistry] = None,
                 chaos=None):
        self.host = host
        self.port = port
        self.subscriber_buffer = subscriber_buffer
        self.drop_limit = max(1, int(drop_limit))
        self.publish_patience_s = publish_patience_s
        self._subs: dict = {}          # topic -> [_Subscriber]
        self._lock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._accept: Optional[ServingLoop] = None
        self._threads: list = []
        self._stop = threading.Event()
        self._chaos = chaos
        # fan-out health counters live in the registry (leaf-locked);
        # broker _lock only guards subscriber bookkeeping
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._m_frames_dropped = self.metrics.counter(
            "broker_frames_dropped_total",
            "frames dropped for slow subscribers")
        self._m_subs_disconnected = self.metrics.counter(
            "broker_subscribers_disconnected_total",
            "slow-subscriber evictions")
        self._m_dropped_by_topic = self.metrics.counter(
            "broker_dropped_by_topic_total",
            "frames dropped per topic", labels=("topic",))
        self.metrics.gauge("broker_subscribers", "live subscribers",
                           fn=self._subscriber_count)

    def _subscriber_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._subs.values())

    def _track(self, t: threading.Thread) -> None:
        """Retain ``t`` for lifecycle introspection, pruning finished
        threads first: a long-lived broker serving N connect/disconnect
        cycles keeps O(live) entries, not O(N) dead Thread objects."""
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "StreamingBroker":
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._server.listen(64)
        self._accept = ServingLoop("broker-accept", tick=self._accept_tick,
                                   chaos=self._chaos)
        self._accept.start()
        self._track(self._accept.threads[-1])
        supervisor().watch(self._accept, on_death=self._on_accept_death,
                           restart=True)
        return self

    def stop(self) -> None:
        """Stop accepting, wake every writer, close every socket. Safe to
        call twice, concurrently, and on a never-started broker."""
        self._stop.set()
        if self._server is not None:
            try:
                # close() alone does NOT wake a thread already blocked in
                # accept() on Linux — shutdown() does (EINVAL in the
                # accepter), so the tick exits now instead of leaking
                # until the join deadline
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()  # accept() raises -> clean tick exit
            except OSError:
                pass
        if self._accept is not None:
            self._accept.close(timeout=1.0)
        with self._lock:
            subs = [s for ss in self._subs.values() for s in ss]
        for s in subs:
            s.alive = False
            try:
                s.sock.close()  # a writer stuck in sendall errors out
            except OSError:
                pass
            if s.loop is not None:
                # the sentinel wakes a writer blocked on an empty queue
                # (no 0.2 s polling); timeout 0 keeps stop() non-blocking
                s.loop.close(timeout=0)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every live subscriber's queue has been written out
        (the broker holds no undelivered frames). False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                subs = [s for ss in self._subs.values() for s in ss]
            if all(s.q.empty() for s in subs if s.alive):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def close(self, timeout: float = 30.0) -> None:
        """Drain undelivered frames, then stop the broker and join its
        runtime loops. Idempotent and re-entrant from any thread."""
        self.drain(timeout)
        with self._lock:
            subs = [s for ss in self._subs.values() for s in ss]
        self.stop()
        deadline = time.monotonic() + max(0.0, timeout)
        loops = [lp for lp in [self._accept] + [s.loop for s in subs]
                 if lp is not None]
        for lp in loops:
            for t in lp.threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))

    def _on_accept_death(self, loop, exc) -> bool:
        """Supervisor hook: restart the accept loop (same listening
        socket) unless the broker is deliberately stopping."""
        return not self._stop.is_set()

    # ------------------------------------------------------------- serving
    def _accept_tick(self) -> bool:
        try:
            conn, _ = self._server.accept()
        except OSError:
            return False  # listening socket closed: clean exit
        t = threading.Thread(target=self._serve, args=(conn,),
                             daemon=True)
        t.start()
        self._track(t)
        return True

    def _serve(self, conn: socket.socket):
        try:
            while True:
                frame = read_frame(conn)
                if frame is None:
                    return
                op, topic, payload = frame
                if op == OP_SUBSCRIBE:
                    self._add_subscriber(conn, topic)
                    return  # connection is now a subscriber: writer owns it
                if op in (OP_PUBLISH, OP_END):
                    self._fan_out(op, topic, payload)
        except (OSError, ValueError):
            pass
        finally:
            if not self._is_subscriber_sock(conn):
                try:
                    conn.close()
                except OSError:
                    pass

    def _is_subscriber_sock(self, conn):
        with self._lock:
            return any(s.sock is conn for ss in self._subs.values()
                       for s in ss)

    def _add_subscriber(self, conn: socket.socket, topic: str):
        sub = _Subscriber(conn, topic, self.subscriber_buffer)
        # the ack is queued BEFORE registration (the queue is private
        # until the sub is in _subs), so it is guaranteed to be frame #1:
        # once the consumer has read it, the subscription is registered
        # and no subsequently published frame can be missed — and no
        # racing publish can slip a data frame ahead of the ack
        sub.q.put((OP_SUB_ACK, b""))
        # the writer is an inbox-mode ServingLoop over the subscriber's
        # own (external) queue, started before registration so _disconnect
        # can never observe a subscriber without a writer loop
        sub.loop = ServingLoop(
            f"broker-writer-{topic}",
            handler=lambda item, s=sub: self._write_frame(s, item),
            inbox=sub.q,
            on_worker_exit=lambda lp, exc, s=sub: self._writer_exit(s),
            chaos=self._chaos)
        sub.loop.start()
        self._track(sub.loop.threads[-1])
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)

    def _write_frame(self, sub: _Subscriber, item):
        """Writer handler: one frame out; EXIT retires the writer on
        end-of-topic or a dead consumer socket."""
        op, payload = item
        try:
            write_frame(sub.sock, op, sub.topic, payload)
        except OSError:
            return EXIT
        if op == OP_END:
            return EXIT
        return None

    def _writer_exit(self, sub: _Subscriber) -> None:
        """Writer retired (end-of-topic, eviction, broker stop, or socket
        error): deregister the subscription and close out the socket."""
        sub.alive = False
        with self._lock:
            ss = self._subs.get(sub.topic, [])
            if sub in ss:
                ss.remove(sub)
        try:
            sub.sock.close()
        except OSError:
            pass

    def _fan_out(self, op: bytes, topic: str, payload: bytes):
        with self._lock:
            subs = list(self._subs.get(topic, []))
        for s in subs:
            self._offer(s, op, payload)

    def _offer(self, s: _Subscriber, op: bytes, payload: bytes):
        """Deliver one frame to one subscriber with bounded backpressure:
        block up to ``publish_patience_s`` (forever when None), then drop
        the frame FOR THIS SUBSCRIBER, count it, and evict the subscriber
        after ``drop_limit`` consecutive drops."""
        limit = (None if self.publish_patience_s is None
                 else time.monotonic() + self.publish_patience_s)
        while s.alive and not self._stop.is_set():
            wait = 0.2 if limit is None else min(
                0.2, limit - time.monotonic())
            if wait <= 0:
                break
            try:
                s.q.put((op, payload), timeout=wait)  # backpressure
                s.consecutive_drops = 0
                return
            except queue.Full:
                continue
        if not s.alive or self._stop.is_set():
            return
        # the patience window closed with the queue still full: this frame
        # is lost to this subscriber — counted, never silent
        with self._lock:
            s.dropped += 1
            s.consecutive_drops += 1
            evict = s.consecutive_drops >= self.drop_limit
        self._m_frames_dropped.inc()
        self._m_dropped_by_topic.labels(topic=s.topic).inc()
        if evict:
            self._disconnect(s)

    def _disconnect(self, s: _Subscriber):
        """Evict a persistently-slow subscriber (it can reconnect): its
        writer thread exits on ``alive=False``, the socket close tells the
        consumer immediately (EOF) rather than leaving it waiting on
        frames that will never come."""
        s.alive = False
        with self._lock:
            ss = self._subs.get(s.topic, [])
            if s in ss:
                ss.remove(s)
        self._m_subs_disconnected.inc()
        try:
            s.sock.close()  # a writer stuck in sendall errors out
        except OSError:
            pass
        if s.loop is not None:
            # bounded: the sentinel wakes a writer blocked on get(); a
            # full queue is skipped (the writer exits via the socket
            # error above) so eviction never stalls the publisher
            s.loop.close(timeout=0)

    def stats(self) -> dict:
        """Fan-out health counters: live subscriber count, frames dropped
        for slow subscribers (total and per topic), and slow-subscriber
        evictions. Counters come off the registry, so the snapshot is
        assembled outside ``_lock``."""
        return {
            "subscribers": self._subscriber_count(),
            "frames_dropped": int(self._m_frames_dropped.value),
            "subscribers_disconnected":
                int(self._m_subs_disconnected.value),
            "dropped_by_topic": {
                lbls["topic"]: int(m.value)
                for lbls, m in self._m_dropped_by_topic.samples()},
        }


def main(argv=None):
    import argparse
    import time

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9092)
    ap.add_argument("--buffer", type=int, default=16,
                    help="per-subscriber frame buffer (backpressure bound)")
    ap.add_argument("--drop-limit", type=int, default=8,
                    help="consecutive dropped frames before a slow "
                         "subscriber is disconnected")
    ap.add_argument("--patience", type=float, default=0.5,
                    help="seconds a publish backpressures on a full "
                         "subscriber queue before dropping the frame "
                         "(<=0: block forever, legacy behavior)")
    args = ap.parse_args(argv)
    broker = StreamingBroker(
        args.host, args.port, args.buffer, drop_limit=args.drop_limit,
        publish_patience_s=None if args.patience <= 0 else args.patience,
    ).start()
    print(f"streaming broker listening on {broker.host}:{broker.port}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        broker.stop()


if __name__ == "__main__":
    main()
