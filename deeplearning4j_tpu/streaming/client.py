"""Publisher/consumer clients + the route builder.

Reference: streaming/kafka/NDArrayPublisher.java (publish NDArrays to a
topic), kafka/NDArrayConsumer.java (getArrays/consume), and
routes/CamelKafkaRouteBuilder.java:16 (wire a record stream into
training). The transport is the in-repo TCP broker
(streaming/broker.py); the payloads are npz-encoded DataSets
(streaming/serde.py).
"""

from __future__ import annotations

import socket
import threading
from typing import Iterator, Optional

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.streaming import QueueDataSetIterator
from deeplearning4j_tpu.streaming.broker import (
    OP_END,
    OP_PUBLISH,
    OP_SUB_ACK,
    OP_SUBSCRIBE,
    read_frame,
    write_frame,
)
from deeplearning4j_tpu.streaming.serde import (
    dataset_from_bytes,
    dataset_to_bytes,
)


class StreamStalled(RuntimeError):
    """A consumer saw no frame within its ``idle_timeout_s`` — the broker
    or publisher is presumed dead/wedged. Raised instead of surfacing a
    silent early end-of-stream to ``fit()``. Defined here (not in
    parallel/resilience.py, which re-exports it into the serving error
    taxonomy) so streaming stays importable without the parallel stack."""


class NDArrayPublisher:
    """Publish DataSet minibatches to a broker topic
    (NDArrayPublisher.java analog; also usable as a context manager)."""

    def __init__(self, host: str, port: int, topic: str,
                 connect_timeout: Optional[float] = 30.0):
        self.topic = topic
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        # the timeout bounds CONNECT only: a publish blocked on broker
        # backpressure for minutes is the documented contract, not an
        # error — the socket must block indefinitely after connect
        self._sock.settimeout(None)

    def publish(self, ds: DataSet) -> None:
        write_frame(self._sock, OP_PUBLISH, self.topic, dataset_to_bytes(ds))

    def publish_arrays(self, features, labels) -> None:
        self.publish(DataSet(features, labels))

    def end(self) -> None:
        """Signal end-of-stream to every subscriber of the topic."""
        write_frame(self._sock, OP_END, self.topic)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NDArrayConsumer:
    """Subscribe to a topic and iterate arriving DataSets until the
    publisher ends the stream (NDArrayConsumer.java analog).

    ``idle_timeout_s`` bounds the wait for the NEXT frame: a dead broker
    otherwise hangs ``__iter__`` forever on a ``settimeout(None)`` socket.
    On idle timeout the iterator raises ``StreamStalled`` — a typed,
    diagnosable failure — rather than hanging or (worse) surfacing a
    silent early end-of-stream to ``fit()``. Default ``None`` keeps the
    block-indefinitely contract for live feeds with long producer idles."""

    def __init__(self, host: str, port: int, topic: str,
                 connect_timeout: Optional[float] = 30.0,
                 idle_timeout_s: Optional[float] = None):
        self.topic = topic
        self.idle_timeout_s = idle_timeout_s
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        try:
            # the handshake stays under connect_timeout (a wedged broker
            # must not hang construction forever)
            write_frame(self._sock, OP_SUBSCRIBE, topic)
            # wait for the broker's registration ack: after this, no
            # frame published to the topic can be missed
            frame = read_frame(self._sock)
            if frame is None or frame[0] != OP_SUB_ACK:
                raise ConnectionError(
                    f"broker did not acknowledge subscription to "
                    f"'{topic}'")
        except BaseException:
            self._sock.close()  # no object escapes: close or leak the fd
            raise
        # from here on, the wait-per-frame policy is the caller's choice:
        # None blocks indefinitely (a producer idling minutes between
        # publishes is normal for a live training feed), a bound turns a
        # dead broker into a typed StreamStalled instead of a silent hang
        self._sock.settimeout(idle_timeout_s)

    def __iter__(self) -> Iterator[DataSet]:
        while True:
            try:
                frame = read_frame(self._sock)
            except socket.timeout:
                raise StreamStalled(
                    f"no frame on topic '{self.topic}' within the "
                    f"{self.idle_timeout_s}s idle timeout — broker or "
                    "publisher presumed dead") from None
            if frame is None:
                return  # broker gone: treat as stream end
            op, _, payload = frame
            if op == OP_END:
                return
            yield dataset_from_bytes(payload)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NDArrayRoute:
    """CamelKafkaRouteBuilder analog: one call wires a broker topic into
    a training-ready iterator. A background thread drains the consumer
    into a bounded QueueDataSetIterator (push-queue backpressure), so
    ``route.iterator()`` plugs straight into ``net.fit(...)`` while a
    producer in another process keeps publishing."""

    def __init__(self, host: str, port: int, topic: str,
                 buffer_batches: int = 16):
        self.consumer = NDArrayConsumer(host, port, topic)
        self._it = QueueDataSetIterator(maxsize=buffer_batches)
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name=f"route-{topic}")
        self._thread.start()

    def _pump(self):
        try:
            for ds in self.consumer:
                self._it.put(ds)
        finally:
            self._it.end()
            self.consumer.close()

    def iterator(self) -> QueueDataSetIterator:
        return self._it

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)
