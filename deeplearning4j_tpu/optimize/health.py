"""Self-healing training: numerical-health guard + host-side recovery.

The reference framework has essentially no numerical failure handling — a
NaN minibatch silently poisons the weights and every iteration after it,
and the fused multi-step driver (optimize/fused_fit.py) amplifies the blast
radius: K minibatches run as ONE donated XLA program, so the host cannot
even observe the corruption until the whole block is done. Production-scale
trainers treat divergence as an expected event with an automated recovery
path (the PaLM training report's loss-spike rewind practice; the
skip-nonfinite update in Optax/T5X-style stacks). This module is that path:

- **Device side** (``all_finite`` / ``tree_select``, fused into the step
  core by ``optimize.fused_fit.build_step_core(guarded=True)``): one
  all-finite reduction over the loss and the gradients per microbatch;
  when non-finite, the identity update is selected for that microbatch —
  params/opt-state/layer-state pass through unchanged inside the scan, so
  the other K-1 steps of a fused block stay good. The per-slot skip flags
  ride back with the block's stacked losses, so a guarded block still
  costs ONE small host fetch.
- **Host side** (``HealthPolicy``): consumes per-block (scores, skips) and
  runs an escalating recovery ladder — an EMA loss-spike detector and a
  consecutive-skip threshold trigger (1) learning-rate backoff via the
  updater's ``scale_lr`` hook, then (2) rollback to the last
  *healthy-gated* checkpoint in an ``elastic.CheckpointStore`` (the
  policy's periodic saves are gated on "no skips since the last save", so
  the newest checkpoint is a true last-known-good), then (3)
  ``DivergenceError`` after ``max_recoveries`` bounded retries.

Wired default-on through ``MultiLayerNetwork.fit`` / ``ComputationGraph
.fit`` (opt-out ``health_guard=None``) and available to ``ParallelWrapper``
mesh training through the same shared step core. Every observation and
recovery action is surfaced through the standard listener interface as
``on_health(model, report)`` (optimize/listeners.py).

Reported scores stay HONEST: a skipped step reports its raw (non-finite)
loss, so score listeners and ``InvalidScoreIterationTerminationCondition``
(earlystopping/termination.py) observe exactly what they always did — the
guard protects the weights, not the telemetry.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.metrics.registry import global_registry


class DivergenceError(RuntimeError):
    """Training diverged and the recovery ladder is exhausted."""


# ---------------------------------------------------------- device helpers
def all_finite(loss, grads):
    """Scalar bool: the loss and every gradient leaf are all-finite.

    One ``isfinite``+``all`` reduction per leaf, combined with logical-and —
    O(num_params) reads against a step that already does O(num_params *
    batch) compute, which is how the guard stays under the 2% overhead
    budget (bench.py ``guard_overhead``)."""
    ok = jnp.all(jnp.isfinite(loss))
    for leaf in jax.tree_util.tree_leaves(grads):
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def tree_select(ok, new, old):
    """``new`` where ``ok`` else ``old``, leafwise over matching pytrees.

    When the structures differ (a TBPTT carry being seeded from ``{}`` on
    the first segment) there is nothing to pass through — return ``new``;
    a poisoned carry only NaNs the remaining segments of that one
    sequence, each of which is then itself skipped, while the parameters
    stay protected."""
    tu = jax.tree_util
    if tu.tree_structure(new) != tu.tree_structure(old):
        return new
    return tu.tree_map(lambda n, o: jnp.where(ok, n, o), new, old)


# ------------------------------------------------------------- host policy
class HealthPolicy:
    """Host-side recovery policy over per-block (score, skipped) streams.

    Recovery ladder, walked once per trigger (consecutive-skip threshold or
    EMA loss spike), bounded by ``max_recoveries``:

    1. LR backoff — ``net.conf.updater.scale_lr(lr_backoff)`` + invalidate
       the compiled step programs (the base lr is baked in at trace time).
    2. Rollback — restore params/updater-state/layer-state/iteration from
       the newest checkpoint in ``store`` (healthy-gated by this policy's
       own saves). The backed-off LR is kept: rewinding to the same
       weights with the same LR would replay the same divergence.
    3. ``DivergenceError`` once ``max_recoveries`` is exhausted (or when
       no rung is available: ``lr_backoff=None`` and no checkpoint).

    Periodic saves: every ``save_frequency`` iterations, IF the window
    since the previous save opportunity saw zero skipped steps — a save
    window containing a skip is dropped (window resets, no checkpoint), so
    ``store.latest()`` is always a last-known-good.
    """

    def __init__(self, *, store=None, save_frequency: int = 100,
                 skip_threshold: int = 8, spike_factor: float = 10.0,
                 ema_alpha: float = 0.1, warmup_steps: int = 20,
                 lr_backoff: Optional[float] = 0.5,
                 max_recoveries: int = 3, registry=None):
        if lr_backoff is not None and not 0.0 < lr_backoff < 1.0:
            raise ValueError(
                f"lr_backoff must be in (0, 1) or None, got {lr_backoff}")
        if skip_threshold < 1:
            raise ValueError("skip_threshold must be >= 1")
        self.store = store
        self.save_frequency = int(save_frequency)
        self.skip_threshold = int(skip_threshold)
        self.spike_factor = float(spike_factor)
        self.ema_alpha = float(ema_alpha)
        self.warmup_steps = int(warmup_steps)
        self.lr_backoff = lr_backoff
        self.max_recoveries = int(max_recoveries)
        # health state — persists across blocks and epochs within one
        # policy instance
        self.ema: Optional[float] = None
        self.warmup_seen = 0
        self.consecutive_skips = 0
        self.total_skips = 0
        self.recoveries = 0
        self.skips_in_window = 0
        self.events: list = []  # every emitted report, for observability
        self._window_start: Optional[int] = None
        self._invalidate = None
        # publish into the shared registry (default: the process-global
        # one, so a serving process scrapes its training health too)
        self.metrics = registry if registry is not None \
            else global_registry()
        self._m_events = self.metrics.counter(
            "health_events_total", "health-guard reports by action",
            labels=("action",))
        self._m_ema = self.metrics.gauge(
            "health_loss_ema", "EMA loss baseline of the spike detector")
        self._m_consecutive = self.metrics.gauge(
            "health_consecutive_skips", "current consecutive skipped steps")
        self._m_total_skips = self.metrics.gauge(
            "health_total_skips", "total device-skipped steps")
        self._m_recoveries = self.metrics.gauge(
            "health_recoveries", "recovery-ladder rungs walked")

    # ------------------------------------------------------------- binding
    def bind(self, net, invalidate=None) -> "HealthPolicy":
        """Attach to a fit loop. ``invalidate`` is an extra program-cache
        invalidation hook for drivers that compile outside the net's
        ``_step_cache`` (ParallelWrapper's round cache)."""
        self._invalidate = invalidate
        return self

    def healthy_to_save(self) -> bool:
        """Gate for external checkpointers (elastic.CheckpointListener):
        True iff no step has been skipped in the current save window."""
        return self.skips_in_window == 0 and self.consecutive_skips == 0

    # --------------------------------------------------------- observation
    def observe(self, net, scores, skips, it0: Optional[int] = None):
        """Consume one block of per-iteration (score, skipped) pairs.

        ``scores``/``skips`` are host arrays (one element per iteration of
        the block — length K fused, 1 unfused, F per ParallelWrapper
        round; ``skips`` entries > 0 mean the device selected the identity
        update). May mutate ``net`` (LR backoff, rollback) and raises
        ``DivergenceError`` when the ladder is exhausted."""
        scores = np.atleast_1d(np.asarray(scores, np.float64))
        skips = np.atleast_1d(np.asarray(skips, np.float64))
        if self._window_start is None:
            self._window_start = (it0 if it0 is not None
                                  else net.iteration - len(scores))
        block_skips = 0
        spike_score = None
        for s, sk in zip(scores, skips):
            if sk > 0:
                block_skips += 1
                self.total_skips += 1
                self.consecutive_skips += 1
                continue
            self.consecutive_skips = 0
            if not np.isfinite(s):
                # cannot happen through the device guard (the loss is part
                # of the all-finite check); defensive for direct callers
                continue
            if (spike_score is None and self.ema is not None
                    and self.warmup_seen >= self.warmup_steps
                    and self.ema > 0
                    and s > self.spike_factor * self.ema):
                # a spike triggers recovery and must NOT drag the EMA
                # baseline up toward itself
                spike_score = float(s)
                continue
            a = self.ema_alpha
            self.ema = (float(s) if self.ema is None
                        else (1.0 - a) * self.ema + a * float(s))
            self.warmup_seen += 1
        self.skips_in_window += block_skips
        if block_skips:
            self._emit(net, {
                "action": "skip", "reason": "nonfinite",
                "iteration": net.iteration,
                "skipped_in_block": block_skips,
                "consecutive_skips": self.consecutive_skips,
                "total_skips": self.total_skips,
            })
        recovered = False
        if self.consecutive_skips >= self.skip_threshold:
            self.recover(net, "skip_threshold",
                         {"consecutive_skips": self.consecutive_skips})
            recovered = True
        elif spike_score is not None:
            self.recover(net, "loss_spike",
                         {"score": spike_score, "ema": self.ema})
            recovered = True
        # healthy-gated periodic checkpoint: an unhealthy window is
        # dropped (no save) and the window restarts, so the newest
        # checkpoint in the store is always a last-known-good
        if (not recovered and self.store is not None
                and net.iteration - self._window_start
                >= self.save_frequency):
            if self.skips_in_window == 0:
                self.store.save(net, {"healthy": True,
                                      "total_skips": self.total_skips})
            self._window_start = net.iteration
            self.skips_in_window = 0

    # ------------------------------------------------------------ recovery
    def recover(self, net, reason: str, detail: dict):
        """Walk one rung of the recovery ladder. Raises DivergenceError
        when retries are exhausted or no rung is available."""
        self.recoveries += 1
        self.consecutive_skips = 0
        report = {"reason": reason, "iteration": net.iteration,
                  "recoveries": self.recoveries,
                  "total_skips": self.total_skips, **detail}
        if self.recoveries > self.max_recoveries:
            self._emit(net, {**report, "action": "raise"})
            raise DivergenceError(
                f"training diverged ({reason} at iteration "
                f"{net.iteration}) and the recovery ladder is exhausted "
                f"after {self.max_recoveries} recoveries "
                f"({self.total_skips} steps skipped in total)")
        if self.recoveries == 1 and self.lr_backoff is not None:
            done = self._do_backoff(net, report)
        else:
            done = (self._do_rollback(net, report)
                    or (self.lr_backoff is not None
                        and self._do_backoff(net, report)))
        if not done:
            self._emit(net, {**report, "action": "raise"})
            raise DivergenceError(
                f"training diverged ({reason} at iteration "
                f"{net.iteration}) and no recovery rung is available "
                "(lr_backoff disabled and no checkpoint to roll back to)")
        # fresh spike baseline after any recovery — the post-recovery loss
        # scale is a new regime
        self.ema = None
        self.warmup_seen = 0

    def _do_backoff(self, net, report: dict) -> bool:
        updater = getattr(net.conf, "updater", None)
        if updater is None or not getattr(updater, "learning_rate", None):
            return False
        lr_before = updater.learning_rate
        lr_after = updater.scale_lr(self.lr_backoff)
        self._invalidate_programs(net)
        self._emit(net, {**report, "action": "lr_backoff",
                         "lr_before": lr_before, "lr_after": lr_after})
        return True

    def _do_rollback(self, net, report: dict) -> bool:
        if self.store is None:
            return False
        restored = self.store.restore()
        if restored is None:
            return False
        ckpt, meta = restored
        # in-place rewind: the live net keeps its conf (and thus the
        # backed-off LR), listeners, and compiled programs — only the
        # trajectory state rewinds
        net.params = ckpt.params
        net.updater_state = ckpt.updater_state
        net.state = ckpt.state
        net.iteration = ckpt.iteration
        self._window_start = net.iteration
        self.skips_in_window = 0
        self._emit(net, {**report, "action": "rollback",
                         "restored_iteration": net.iteration,
                         "checkpoint_meta": meta})
        return True

    def _invalidate_programs(self, net):
        # the base lr is a trace-time constant of every compiled step
        cache = getattr(net, "_step_cache", None)
        if cache is not None:
            cache.clear()
        if self._invalidate is not None:
            self._invalidate()

    # -------------------------------------------------------------- events
    def _emit(self, net, report: dict):
        self.events.append(report)
        self._m_events.labels(action=report.get("action", "unknown")).inc()
        self._m_ema.set(self.ema if self.ema is not None else 0.0)
        self._m_consecutive.set(self.consecutive_skips)
        self._m_total_skips.set(self.total_skips)
        self._m_recoveries.set(self.recoveries)
        for listener in getattr(net, "listeners", []) or []:
            hook = getattr(listener, "on_health", None)
            if hook is not None:
                hook(net, dict(report))


def resolve_health_policy(health_guard) -> Optional[HealthPolicy]:
    """``fit(health_guard=...)`` coercion: True -> a default policy,
    None/False -> guard off, a HealthPolicy -> itself."""
    if health_guard is None or health_guard is False:
        return None
    if health_guard is True:
        return HealthPolicy()
    if isinstance(health_guard, HealthPolicy):
        return health_guard
    raise TypeError(
        "health_guard must be True (default policy), None/False (guard "
        f"off), or a HealthPolicy instance; got {health_guard!r}")
