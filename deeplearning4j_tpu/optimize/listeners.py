"""Training listeners.

Reference: optimize/api/IterationListener.java + TrainingListener.java (hooks fired
by the optimizer, e.g. ComputationGraph.java:1192-1235) and the impls under
optimize/listeners/ (ScoreIterationListener, PerformanceListener, EvaluativeListener,
CollectScoresIterationListener, TimeIterationListener, ModelSavingCallback).

Block semantics under the fused fit path (optimize/fused_fit.py): ``fit``
compiles K SGD steps into one device program, so scores materialize per
BLOCK — one host fetch of the stacked loss array per K iterations.
``iteration_done`` still fires once per iteration (with ``model.score_value``
set to that iteration's score), but model parameters observed inside the
hook are the END-OF-BLOCK parameters. Listeners that want the whole stacked
score array at once override ``on_block_done``.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

log = logging.getLogger(__name__)


class TrainingListener:
    """Base listener. Subclasses override any subset of hooks."""

    def iteration_done(self, model, iteration: int):
        pass

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def on_block_done(self, model, iterations: list, scores):
        """Fired once per fused K-step block, BEFORE the per-iteration
        ``iteration_done`` calls for that block. ``iterations`` is the list
        of iteration numbers the block ran; ``scores`` the matching numpy
        score array (one device fetch for the whole block). ``model``
        carries end-of-block parameters."""
        pass

    def on_phase_timings(self, model, timings: dict):
        """Per-round training-phase wall times (reference:
        spark/api/stats/SparkTrainingStats.java — data-fetch / fit /
        aggregation timings per worker round). ``timings`` carries ms
        floats, e.g. {"host_prep_ms": ..., "device_round_ms": ...}."""
        pass

    def on_health(self, model, report: dict):
        """Fired by the numerical-health guard (optimize/health.py) when
        it observes skipped (non-finite) steps or takes a recovery action.
        ``report["action"]`` is one of ``"skip"``, ``"lr_backoff"``,
        ``"rollback"``, ``"raise"``; the remaining keys carry the trigger
        (``reason``), the iteration, and action-specific detail (skip
        counts, lr before/after, restored iteration)."""
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference: ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration: int):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration, model.score_value)


class PerformanceListener(TrainingListener):
    """Throughput (examples/sec, iterations/sec) every N iterations (reference:
    optimize/listeners/PerformanceListener.java)."""

    def __init__(self, frequency: int = 10, report_batch: bool = True):
        self.frequency = max(1, frequency)
        self.report_batch = report_batch
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self.last_samples_per_sec: Optional[float] = None
        self.batch_size: int = 0

    def iteration_done(self, model, iteration: int):
        now = time.perf_counter()
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0 and iters > 0:
                it_per_sec = iters / dt
                self.last_samples_per_sec = it_per_sec * self.batch_size
                log.info("iteration %d: %.1f iter/s, %.1f samples/s", iteration,
                         it_per_sec, self.last_samples_per_sec or 0.0)
            self._last_time = now
            self._last_iter = iteration
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration


class CollectScoresIterationListener(TrainingListener):
    """Collect (iteration, score) pairs (reference: CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: list = []

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score_value))


class EvaluativeListener(TrainingListener):
    """Periodically evaluate on a held-out iterator (reference: EvaluativeListener)."""

    def __init__(self, eval_iterator, frequency: int = 100, callback=None):
        self.eval_iterator = eval_iterator
        self.frequency = max(1, frequency)
        self.callback = callback
        self.evaluations: list = []

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0:
            ev = model.evaluate(self.eval_iterator)
            self.evaluations.append((iteration, ev))
            if self.callback:
                self.callback(model, ev)
            else:
                log.info("Eval at iter %d: accuracy=%.4f f1=%.4f", iteration,
                         ev.accuracy(), ev.f1())


class TimeIterationListener(TrainingListener):
    """Estimate remaining time (reference: TimeIterationListener)."""

    def __init__(self, total_iterations: int, frequency: int = 50):
        self.total_iterations = total_iterations
        self.frequency = max(1, frequency)
        self._start = time.perf_counter()

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = elapsed / iteration
            remaining = (self.total_iterations - iteration) * rate
            log.info("iteration %d/%d, ~%.0fs remaining", iteration,
                     self.total_iterations, remaining)


class ProfilerListener(TrainingListener):
    """Capture a jax.profiler device trace for a window of iterations
    (SURVEY §5 tracing: the reference has only wall-clock listeners; on TPU
    the jax profiler gives per-op device timelines viewable in
    TensorBoard/Perfetto). Starts at ``start_iteration``, stops after
    ``num_iterations``; writes to ``log_dir``."""

    def __init__(self, log_dir: str, start_iteration: int = 10,
                 num_iterations: int = 5):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.stop_iteration = start_iteration + num_iterations
        self._active = False

    def iteration_done(self, model, iteration: int):
        import jax

        if not self._active and iteration >= self.start_iteration \
                and iteration < self.stop_iteration:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.stop_iteration:
            jax.profiler.stop_trace()
            self._active = False
            log.info("profiler trace written to %s", self.log_dir)


class HealthListener(TrainingListener):
    """Collect (and optionally log) health-guard reports — skipped
    non-finite steps, LR backoffs, checkpoint rollbacks — emitted by
    ``optimize.health.HealthPolicy`` through the standard listener
    interface. Attach like any other listener; ``reports`` accumulates
    every event dict in order."""

    def __init__(self, log_events: bool = True):
        self.reports: list = []
        self.log_events = log_events

    def on_health(self, model, report: dict):
        self.reports.append(report)
        if self.log_events:
            log.warning("health event at iteration %s: %s",
                        report.get("iteration"), report)


class ModelSavingCallback(TrainingListener):
    """Save checkpoints every N iterations (reference:
    optimize/listeners/callbacks/ModelSavingCallback.java)."""

    def __init__(self, path_template: str, frequency: int = 1000):
        self.path_template = path_template
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0:
            from deeplearning4j_tpu.utils.model_serializer import save_model
            save_model(model, self.path_template.format(iteration=iteration))
