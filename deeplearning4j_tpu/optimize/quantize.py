"""Post-training int8 quantization of inference params.

The reference framework's accelerator story is a swappable compute
backend under an unchanged layer API (the cuDNN ``*Helper`` pattern —
ConvolutionLayer.java:68-79 loads an accelerated implementation by
reflection and the f32 layer contract never moves). The JAX-native
equivalent built here is a quantized EXECUTION PATH behind the same
``output()``/``submit()`` surfaces:

- ``quantize_params(net) -> (qparams, scales)`` rewrites the dense /
  conv / attention-projection weights to absmax per-output-channel int8
  (``scale = absmax / 127`` over every axis but the last), leaving
  biases, norms, embeddings and recurrent cells untouched. The int8
  tensor and its ``"<name>_scale"`` sibling ride the SAME params pytree,
  so every existing jit program keyed on params structure simply
  retraces once for the quantized tree — no new program plumbing.
- Layers detect quantization at TRACE time (``"W_scale" in params`` is a
  pytree-structure check, part of the jit cache key) and fuse the
  dequant into the matmul/conv: ``(x @ W_q.astype(x)) * scale``, which
  XLA folds into the epilogue of the GEMM — the weights stay int8 in
  memory, 4x smaller, and are widened on the fly.
- ``quantize_net(net)`` returns a servable shallow copy whose params are
  quantized; the source net is untouched, so an f32 fleet can A/B a
  quantized replica against bit-exact originals.

Quantized outputs are NOT bit-exact vs f32 — they are gated on bounded
eval deltas instead: ``confusion_delta`` (fraction of examples that
moved confusion-matrix cells between two ``Evaluation``s) and
``greedy_agreement`` (fraction of positions two greedy completions
agree on). tests/test_quantize.py and the ``quant_serve`` bench assert
those gates; everything with quantization OFF stays bit-exact.
"""

from __future__ import annotations

import copy

import numpy as np

#: suffix marking a dequant scale riding next to its int8 tensor in the
#: params tree — layers key the fused-dequant path on its presence
SCALE_SUFFIX = "_scale"


def quantize_array(w):
    """Absmax per-output-channel int8: ``(q, scale)`` with the scale
    reduced over every axis but the LAST (the output-channel axis for
    all quantizable layouts here: ``[in, out]`` dense, ``[kh, kw, in,
    out]`` HWIO conv, ``[d_model, d_model]`` attention projections).

    ``q * scale`` reconstructs ``w`` to within half a quantization step
    per channel. All-zero channels get scale 0 (and reconstruct as
    exact zeros). Runs on host numpy — quantization is a one-shot
    model-load transform, not a traced op."""
    import jax.numpy as jnp

    w = np.asarray(w)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1))) \
        if w.ndim > 1 else np.abs(w)
    scale = (absmax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(w / safe), -127, 127).astype(np.int8)
    return jnp.asarray(q), jnp.asarray(scale)


def dequantize_array(q, scale, dtype=np.float32):
    """Reconstruct ``q * scale`` (the reference the fused path folds
    into its matmul epilogue) — for tests and round-trip bounds."""
    return (np.asarray(q).astype(np.float32) * np.asarray(scale)).astype(
        dtype)


def _layer_items(net):
    """(params key, layer) pairs keyed exactly as ``net.params`` —
    positional ``str(i)`` for MultiLayerNetwork, vertex name for
    ComputationGraph (same vocabulary as ``_stream_layers``)."""
    if hasattr(net, "layers"):
        for i, layer in enumerate(net.layers):
            yield str(i), layer
    else:
        for name, v in net.conf.vertices.items():
            layer = getattr(v, "layer", None)
            if layer is not None:
                yield name, layer


def quantize_params(net):
    """Quantize every ``QUANT_PARAMS`` weight of ``net`` (dense W, conv
    kernels, attention Wq/Wk/Wv/Wo — layers opt in via the class
    attribute, so embeddings / norms / recurrent cells never quantize).

    Returns ``(qparams, scales)``: ``qparams`` is directly servable —
    the original params tree with each quantized tensor replaced by its
    int8 form plus a ``"<name>_scale"`` sibling — and ``scales`` is a
    plain ``{layer_key: {param_name: scale}}`` side dict for
    inspection."""
    qparams = {k: dict(v) if isinstance(v, dict) else v
               for k, v in net.params.items()}
    scales: dict = {}
    for key, layer in _layer_items(net):
        names = getattr(layer, "QUANT_PARAMS", ())
        lp = qparams.get(key)
        if not names or not isinstance(lp, dict):
            continue
        for pname in names:
            w = lp.get(pname)
            if w is None:
                continue
            q, scale = quantize_array(w)
            lp[pname] = q
            lp[pname + SCALE_SUFFIX] = scale
            scales.setdefault(key, {})[pname] = scale
    return qparams, scales


def quantize_net(net, mode: str = "int8"):
    """A servable copy of ``net`` with int8-quantized weights.

    The copy is shallow: conf, state and the compiled-program caches are
    shared (programs take params as jit ARGUMENTS, so the quantized
    pytree structure retraces exactly once per program family and both
    nets keep their own correct math). The source net's params are
    untouched — its outputs stay bit-exact. Inference-only: fitting a
    quantized net would try to take gradients through int8 weights."""
    if mode != "int8":
        raise ValueError(f"unsupported quantization mode {mode!r} "
                         "(only 'int8')")
    qparams, _ = quantize_params(net)
    qnet = copy.copy(net)
    qnet.params = qparams
    return qnet


# ------------------------------------------------------- accuracy gates
def confusion_delta(ev_a, ev_b) -> float:
    """Fraction of evaluated examples that changed confusion-matrix
    cells between two ``Evaluation`` results (0.0 = identical
    classifications). The eval-parity gate for quantized weights."""
    cm_a = ev_a.confusion if hasattr(ev_a, "confusion") else ev_a
    cm_b = ev_b.confusion if hasattr(ev_b, "confusion") else ev_b
    cm_a = np.zeros((1, 1), np.int64) if cm_a is None else np.asarray(cm_a)
    cm_b = np.zeros((1, 1), np.int64) if cm_b is None else np.asarray(cm_b)
    if cm_a.shape != cm_b.shape:
        raise ValueError(f"confusion shapes differ: {cm_a.shape} vs "
                         f"{cm_b.shape}")
    n = cm_a.sum()
    if n != cm_b.sum():
        raise ValueError("evaluations cover different example counts: "
                         f"{n} vs {cm_b.sum()}")
    if n == 0:
        return 0.0
    # each moved example leaves one cell and enters another
    return float(np.abs(cm_a - cm_b).sum()) / (2.0 * float(n))


def greedy_agreement(a, b) -> float:
    """Fraction of aligned positions where two greedy completions pick
    the same token (length mismatch counts the missing tail as
    disagreement). The generation gate for int8 KV-caches."""
    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    n = min(a.size, b.size)
    hi = max(a.size, b.size)
    if hi == 0:
        return 1.0
    return float(np.count_nonzero(a[:n] == b[:n])) / float(hi)
