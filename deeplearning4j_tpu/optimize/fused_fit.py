"""Fused multi-step training driver: K SGD steps as ONE XLA program.

The per-minibatch ``do_step`` path pays one Python dispatch, one host->device
transfer, and one listener round-trip per minibatch. The reference hides the
ETL half of that with ``AsyncDataSetIterator`` background prefetch
(datasets/iterator/AsyncDataSetIterator.java:30); the TPU-idiomatic completion
implemented here fuses the dispatch half too:

- ``build_step_core`` — the single functional SGD step (forward, loss,
  jax.grad, regularization, gradient normalization, updater, center-loss
  update) shared by the unfused jitted step (``MultiLayerNetwork._make_step``
  and the ComputationGraph twin), the fused K-step scan below, and
  ``ParallelWrapper``'s data-parallel device round — one definition, three
  drivers, no drift.
- ``build_fused_step`` — K stacked microbatches compiled as one jitted,
  buffer-donating program (``lax.scan``; unrolled at trace time on CPU,
  where XLA pessimizes compute inside control-flow bodies). Only FULL
  K-blocks are dispatched to it; a trailing group of fewer than K
  microbatches takes the per-minibatch path, which beats any in-program
  dead-slot skip (see ``FusedFitDriver``).
- ``FusedFitDriver`` — host-side block assembly with batch-shape BUCKETING
  (trailing partial batches are padded up to the bucket batch size with
  zeroed label-mask rows, so ``_step_cache`` holds ONE program across a
  ragged epoch) plus double-buffered device prefetch (``jax.device_put``
  dispatches asynchronously; issuing the next block's transfer while the
  current block trains overlaps copy with compute).

Listener semantics under fusion: listeners still fire once per iteration,
but scores materialize per BLOCK — one device fetch of the stacked loss
array per K steps instead of one per step. Listener hooks therefore observe
end-of-block parameters. Listeners wanting the whole stacked array get it
via ``TrainingListener.on_block_done``.
"""

from __future__ import annotations

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.nn.gradient_normalization import (
    apply_gradient_normalization,
    layer_map_for,
)
from deeplearning4j_tpu.nn.regularization import add_regularization_grads

#: default K for ``fit(..., fused_steps=None)`` — the fused fast path is the
#: default; pass ``fused_steps=1`` to opt out (pure per-minibatch do_step).
DEFAULT_FUSED_STEPS = 4

#: CPU default. Measured on XLA:CPU (LeNet, single core): per-step cost of
#: the fused program grows with the unroll factor (K=2 is ~flat, K=4 is
#: 1.4-2x a single step — LLVM code-size/cache effects), so larger K LOSES
#: throughput. K=2 keeps the one-program-per-ragged-epoch property and the
#: block-level score fetch while staying at the measured sweet spot.
DEFAULT_FUSED_STEPS_CPU = 2


def resolve_fused_steps(net, fused_steps):
    """Effective K for a fit call. TBPTT carries hidden state across
    segments host-side, so it stays on the unfused path regardless."""
    if fused_steps is None:
        k = (DEFAULT_FUSED_STEPS_CPU if jax.default_backend() == "cpu"
             else DEFAULT_FUSED_STEPS)
    else:
        k = int(fused_steps)
        if k < 1:
            raise ValueError(f"fused_steps must be >= 1, got {fused_steps}")
    if getattr(net.conf, "backprop_type", "standard") == "tbptt":
        return 1
    return k


# --------------------------------------------------------------- step core
def _center_spec(net):
    """(kind, key(s)) of CenterLossOutputLayer heads needing the non-gradient
    center update, or None. Works for both MultiLayerNetwork (layers list)
    and ComputationGraph (vertices dict)."""
    from deeplearning4j_tpu.nn.conf.layers.misc import CenterLossOutputLayer

    layers = getattr(net, "layers", None)
    if isinstance(layers, list):
        if layers and isinstance(layers[-1], CenterLossOutputLayer):
            return ("mln", str(len(layers) - 1))
        return None
    conf = net.conf
    if hasattr(conf, "network_outputs") and hasattr(conf, "vertices"):
        from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex

        outs = [n for n in conf.network_outputs
                if isinstance(conf.vertices[n], LayerVertex)
                and isinstance(conf.vertices[n].layer, CenterLossOutputLayer)]
        if outs:
            return ("graph", outs)
    return None


def build_step_core(net, *, grad_transform=None, guarded=False):
    """One functional SGD step over ``net``'s ``_loss`` contract.

    Returns ``core(params, opt_state, state, rng, iteration, x, y,
    input_mask, label_mask, carry) -> (new_params, new_opt, new_states,
    new_carry, loss)``. ``grad_transform`` (e.g. a ``lax.pmean``) is applied
    between the closed-form regularization grads and gradient normalization
    — the ordering ParallelWrapper's SHARED_GRADIENTS parity contract needs.

    With ``guarded=True`` the core additionally runs the numerical-health
    guard (optimize/health.py): one all-finite reduction over the loss and
    the post-transform gradients; when non-finite, the IDENTITY update is
    selected (params/opt-state/layer-state/carry pass through unchanged)
    and the returned tuple gains a trailing ``skip`` scalar (1.0 when the
    step was skipped) — ``(..., loss, skip)``. The finite check sits after
    ``grad_transform`` so a SHARED_GRADIENTS ``pmean`` poisons (and skips)
    all replicas identically, keeping them in lockstep. The raw (possibly
    non-finite) loss is still reported: the guard protects the weights,
    not the telemetry. On the all-finite path the select returns the new
    trees exactly, so guarded and unguarded trajectories are bit-identical.
    """
    from deeplearning4j_tpu.optimize.health import all_finite, tree_select

    updater = net.conf.updater
    lr_mults = net._lr_mult_tree() if hasattr(net, "_lr_mult_tree") else None
    layer_map = layer_map_for(net)
    center = _center_spec(net)

    def core(params, opt_state, state, rng, iteration, x, y, input_mask,
             label_mask, carry):
        def loss_fn(p):
            return net._loss(p, state, x, y, input_mask, label_mask,
                             train=True, rng=rng, carry=carry)

        (loss, (new_states, new_carry, last_in)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = add_regularization_grads(net, params, grads)
        if grad_transform is not None:
            grads = grad_transform(grads)
        if guarded:
            ok = all_finite(loss, grads)
        grads = apply_gradient_normalization(layer_map, grads)
        if lr_mults is not None:
            steps, opt_state2 = updater.step(grads, opt_state, iteration,
                                             lr_mults)
        else:
            steps, opt_state2 = updater.step(grads, opt_state, iteration)
        new_params = jax.tree_util.tree_map(lambda p, s: p - s, params, steps)
        if center is not None:
            kind, keys = center
            if kind == "mln":
                new_states[keys] = net.layers[-1].update_centers(
                    state[keys], last_in, y)
            else:
                outs = net.conf.network_outputs
                for name in keys:
                    j = outs.index(name)
                    yy = y[j] if isinstance(y, (list, tuple)) else y
                    new_states[name] = net.conf.vertices[name].layer \
                        .update_centers(state[name], last_in[name], yy)
        if guarded:
            # identity update on a poisoned step: everything the step
            # would have mutated passes through unchanged
            new_params = tree_select(ok, new_params, params)
            opt_state2 = tree_select(ok, opt_state2, opt_state)
            new_states = tree_select(ok, new_states, state)
            new_carry = tree_select(ok, new_carry, carry)
            skip = 1.0 - ok.astype(jnp.float32)
            return (new_params, opt_state2, new_states, new_carry, loss,
                    skip)
        return new_params, opt_state2, new_states, new_carry, loss

    return core


def make_scan_body(core, *, rng_fn, guarded=False):
    """``lax.scan`` body over ``core``. Carry is ``(params, opt_state,
    state, iteration)``; each scan slot is ``(x, y, im, lm)``. Every slot
    is a real step — the fused driver only dispatches FULL K-blocks
    through the scan (a trailing partial block takes the per-minibatch
    path instead), so the body needs no per-slot dead-slot machinery: a
    ``lax.cond`` skip was measured to pessimize the whole body 5x on
    XLA:CPU, and a select-based skip pays full dead-slot FLOPs plus a
    param-tree copy on every live step. (The health guard's where-select
    is different: it fires only on NON-FINITE steps, a correctness
    feature, and its cost is bounded by bench.py ``guard_overhead``.)

    With ``guarded=True`` (a ``build_step_core(guarded=True)`` core) the
    per-slot output is the ``(loss, skip)`` pair instead of the bare loss,
    so a fused block surfaces its per-step skip flags stacked alongside
    the stacked losses — still one host fetch per block. The iteration
    counter advances on skipped steps too, keeping the ``fold_in(base_key,
    iteration)`` RNG stream — and therefore fused/unfused bit-parity —
    independent of where the bad batch landed."""

    def body(carry, inp):
        params, opt_state, state, it = carry
        x, y, im, lm = inp
        rng = rng_fn(it)
        if guarded:
            p2, o2, s2, _, loss, skip = core(params, opt_state, state, rng,
                                             it, x, y, im, lm, None)
            return (p2, o2, s2, it + 1.0), (loss, skip)
        p2, o2, s2, _, loss = core(params, opt_state, state, rng, it,
                                   x, y, im, lm, None)
        return (p2, o2, s2, it + 1.0), loss

    return body


def _unroll_fused() -> bool:
    """Whether the fused program should be traced as straight-line code.

    XLA:CPU pessimizes compute inside ``while`` bodies — a LeNet train step
    measured 5x slower under ``lax.scan`` than the identical step as
    top-level HLO, and ``unroll=K`` does not help (the single-trip while
    remains). On CPU the K steps are therefore unrolled at trace time
    (program size O(K), per-step cost identical to the unfused step); on
    TPU/GPU the rolled scan is kept for O(1) program size and compile
    time."""
    return jax.default_backend() == "cpu"


def build_fused_step(net, guarded=False):
    """The fused K-step program: one jitted, buffer-donating K-step loop
    (``lax.scan``, unrolled at trace time on CPU — see ``_unroll_fused``).

    ``fused(params, opt_state, state, base_key, it0, xs, ys, ims, lms)
    -> (params, opt_state, state, losses[K])`` — with ``guarded=True``
    the health guard rides inside the program and the outputs gain a
    trailing ``skips[K]`` stack (see ``build_step_core``). ``xs/ys/ims/
    lms`` are [K, B, ...] stacks (ims/lms may be None — static, baked per
    jit signature). The per-slot rng is ``fold_in(base_key, iteration)``
    — bit-identical to the unfused ``do_step`` path, so fused and unfused
    trajectories match."""
    core = build_step_core(net, guarded=guarded)

    def fused(params, opt_state, state, base_key, it0, xs, ys, ims, lms):
        body = make_scan_body(
            core,
            rng_fn=lambda it: jax.random.fold_in(base_key,
                                                 it.astype(jnp.int32)),
            guarded=guarded)
        carry = (params, opt_state, state, it0)
        if _unroll_fused():
            outs = []
            for k in range(xs.shape[0]):  # static index -> straight-line HLO
                carry, out = body(carry, (xs[k], ys[k],
                                          None if ims is None else ims[k],
                                          None if lms is None else lms[k]))
                outs.append(out)
            if guarded:
                losses = jnp.stack([o[0] for o in outs])
                skips = jnp.stack([o[1] for o in outs])
            else:
                losses = jnp.stack(outs)
        else:
            carry, scanned = lax.scan(body, carry, (xs, ys, ims, lms))
            if guarded:
                losses, skips = scanned
            else:
                losses = scanned
        params, opt_state, state, _ = carry
        if guarded:
            return params, opt_state, state, losses, skips
        return params, opt_state, state, losses

    # params/opt/state are dead after the call (the driver rebinds them from
    # the outputs) — donation updates the model in place across all K steps
    return jax.jit(fused, donate_argnums=(0, 1, 2))


# ------------------------------------------------------------ host pipeline
def device_put_ahead(items, depth: int, place):
    """Bounded look-ahead device placement: keep ``depth`` placed items in
    flight while the consumer works on the current one. ``jax.device_put``
    dispatches asynchronously, so issuing the puts ahead pipelines the
    host->device copies behind the running computation — the on-device
    analogue of AsyncDataSetIterator's host-side queue."""
    if depth < 1:
        raise ValueError("depth must be >= 1")
    it = iter(items)
    buf: deque = deque()
    try:
        for _ in range(depth):
            buf.append(place(next(it)))
    except StopIteration:
        pass
    while buf:
        nxt = buf.popleft()
        try:
            buf.append(place(next(it)))  # dispatch ahead, async
        except StopIteration:
            pass
        yield nxt


class FusedFitDriver:
    """Consumes a stream of DataSets as fused K-step blocks.

    Shape bucketing: the first usable batch fixes the bucket (batch size +
    trailing dims + mask signature). Undersized batches — the ragged tail
    of an epoch — are padded UP to the bucket batch size by replicating the
    last row (real data, so no degenerate activations) with ZEROED
    label-mask rows, so the masked loss mean and its gradients are exactly
    those of the unpadded batch and ``_step_cache`` keeps ONE program
    across a ragged epoch. A label mask is synthesized (all ones) for
    unmasked streams so full and padded blocks share one jit signature.

    Only FULL K-blocks go through the fused program: a trailing group of
    fewer than K microbatches runs through the per-minibatch ``_fit_batch``
    path instead. Skipping dead scan slots in-program costs more than it
    saves — ``lax.cond`` pessimizes the whole body 5x on XLA:CPU, and
    select-masking pays full dead-slot FLOPs plus a param-tree copy per
    live step — while the unfused tail pays at most K-1 per-step
    dispatches once per stream.

    The one stream shape bucketing does NOT cover: features_mask present
    without labels_mask — a synthesized label mask would override the
    propagated feature mask the loss otherwise uses, so undersized batches
    there fall back to the unfused ``_fit_batch`` path (correct, one extra
    compile). Batches that don't fit the bucket at all (MultiDataSet,
    different trailing dims, larger than bucket) also fall back, after the
    pending microbatches are flushed so update order is preserved.
    """

    def __init__(self, net, fused_steps: int, prefetch_depth: int = 2):
        if fused_steps < 1:
            raise ValueError("fused_steps must be >= 1")
        self.net = net
        self.K = fused_steps
        self.depth = max(1, prefetch_depth)

    # ------------------------------------------------------------- assembly
    def _blocks(self, batches):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        bucket = None
        pend: list = []  # (padded arrays, original DataSet) pairs
        for ds in batches:
            item = None
            if isinstance(ds, DataSet) and ds.labels is not None:
                f = np.asarray(ds.features)
                l = np.asarray(ds.labels)
                im = (None if ds.features_mask is None
                      else np.asarray(ds.features_mask))
                lm = (None if ds.labels_mask is None
                      else np.asarray(ds.labels_mask))
                if bucket is None:
                    bucket = (f.shape[0], f.shape[1:], l.shape[1:],
                              im is not None, lm is not None)
                B, ftail, ltail, has_im, has_lm = bucket
                fits = (f.shape[1:] == ftail and l.shape[1:] == ltail
                        and (im is not None) == has_im
                        and (lm is not None) == has_lm
                        and f.shape[0] <= B)
                # synthesizing a label mask is only sound when it cannot
                # shadow a propagated feature mask (see class docstring)
                synth_lm = not has_lm and not has_im
                if fits and (f.shape[0] == B or has_lm or synth_lm):
                    item = self._pad_micro(f, l, im, lm, B, ltail, synth_lm)
            if item is not None:
                pend.append((item, ds))
                if len(pend) == self.K:
                    yield ("block", self._stack([it for it, _ in pend]))
                    pend = []
                continue
            if pend:  # flush before the fallback batch: updates stay ordered
                yield ("tail", [d for _, d in pend])
                pend = []
            yield ("raw", ds)
        if pend:
            # fewer than K microbatches left: the per-minibatch path (see
            # class docstring — cheaper than dead scan slots)
            yield ("tail", [d for _, d in pend])

    @staticmethod
    def _pad_micro(f, l, im, lm, B, ltail, synth_lm):
        pad = B - f.shape[0]
        if synth_lm or (lm is None and pad):
            lm = np.ones((f.shape[0],) + ltail[:-1], np.float32)
        if pad:
            def rep(a):
                return np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])

            f, l = rep(f), rep(l)
            if im is not None:
                im = rep(im)
            if lm is not None:
                lm = np.concatenate(
                    [lm, np.zeros((pad,) + lm.shape[1:], lm.dtype)])
        return (f, l, im, lm)

    def _stack(self, items):
        def stack(j):
            if items[0][j] is None:
                return None
            return np.stack([r[j] for r in items])

        return (stack(0), stack(1), stack(2), stack(3))

    # ------------------------------------------------------------ execution
    def _place(self, tagged):
        tag, payload = tagged
        if tag != "block":
            return tagged
        # ONE device_put over the whole block pytree: one async dispatch,
        # issued `depth` blocks ahead so the copy overlaps device compute
        return ("block", jax.device_put(payload))

    def fit_stream(self, batches) -> int:
        """Train over one stream of DataSets; returns iterations run."""
        net = self.net
        start = net.iteration
        for tag, payload in device_put_ahead(self._blocks(batches),
                                             self.depth, self._place):
            if tag == "block":
                self._run_block(*payload)
            elif tag == "tail":
                for ds in payload:
                    net._fit_batch(ds)
            else:
                net._fit_batch(payload)
        return net.iteration - start

    def _run_block(self, xs, ys, ims, lms):
        net = self.net
        K = self.K
        health = getattr(net, "_health", None)
        guarded = health is not None
        key = ("fused", K, xs.shape, ys.shape,
               ims is not None, lms is not None, guarded)
        fused = net._get_step(key)
        it0 = net.iteration
        out = fused(
            net.params, net.updater_state, net.state, net._rng_base(),
            jnp.asarray(it0, jnp.float32), xs, ys, ims, lms)
        skips_h = None
        if guarded:
            net.params, net.updater_state, net.state, losses, skips = out
        else:
            net.params, net.updater_state, net.state, losses = out
        net.iteration += K
        listeners = net.listeners
        if not listeners and not guarded:
            # device scalar, no host sync — see the score_value contract
            net.score_value = losses[K - 1]
            return
        if guarded:
            # still ONE host fetch per block: the stacked losses and the
            # stacked skip flags come back together. Observe BEFORE the
            # listener round so health-gated checkpoint listeners see this
            # block's skip state, and a recovery (or DivergenceError)
            # precedes — or suppresses — the block's listener dispatch.
            scores, skips_h = map(np.asarray,
                                  jax.device_get((losses, skips)))
            health.observe(net, scores, skips_h, it0)
        else:
            # ONE device fetch per block (not one per step): the whole
            # stacked loss array comes back, then listeners fire per step
            scores = np.asarray(losses)
        if not listeners:
            # no listeners: score_value keeps the device-side contract
            net.score_value = losses[K - 1]
        else:
            iters = list(range(it0 + 1, it0 + K + 1))
            for listener in listeners:
                if hasattr(listener, "on_block_done"):
                    listener.on_block_done(net, iters, scores)
            for k, it in enumerate(iters):
                net.score_value = scores[k]
                for listener in listeners:
                    listener.iteration_done(net, it)
