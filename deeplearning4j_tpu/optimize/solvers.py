"""Batch optimizers beyond SGD: line search, CG, L-BFGS.

Reference: optimize/Solver.java (facade), optimize/solvers/BaseOptimizer.java
:170-247 (gradientAndScore + line-search step), StochasticGradientDescent.java
:60-80, LineGradientDescent.java, ConjugateGradient.java, LBFGS.java,
BackTrackLineSearch.java, optimize/stepfunctions/*, optimize/terminations/*.

TPU-native: the model's loss is a pure function of the flat parameter vector
(ravel_pytree); value-and-gradient is one jitted program, and each optimizer
is host-side control flow over device-resident vectors — the natural split
(line-search trial counts are data-dependent, so they stay out of XLA).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


# ----------------------------------------------------------------- terminations
class TerminationCondition:
    """reference: optimize/terminations/"""

    def terminate(self, cost: float, old_cost: float, other=None) -> bool:
        raise NotImplementedError


class EpsTermination(TerminationCondition):
    """|old - new| <= eps * (|old| + |new| + eps) (reference:
    EpsTermination.java)."""

    def __init__(self, eps: float = 1e-10, tolerance: float = 1e-5):
        self.eps = eps
        self.tolerance = tolerance

    def terminate(self, cost, old_cost, other=None):
        return 2.0 * abs(old_cost - cost) <= self.tolerance * (
            abs(old_cost) + abs(cost) + self.eps)


class Norm2Termination(TerminationCondition):
    """Gradient L2 below threshold (reference: Norm2Termination.java)."""

    def __init__(self, gradient_norm_threshold: float = 1e-8):
        self.threshold = gradient_norm_threshold

    def terminate(self, cost, old_cost, other=None):
        return other is not None and float(other) < self.threshold


class ZeroDirection(TerminationCondition):
    def terminate(self, cost, old_cost, other=None):
        return other is not None and float(other) == 0.0


# ------------------------------------------------------------------ line search
class BackTrackLineSearch:
    """Backtracking line search with Armijo sufficient-decrease (reference:
    optimize/solvers/BackTrackLineSearch.java — relax constant ALF=1e-4,
    step contraction, maxIterations)."""

    def __init__(self, loss_fn: Callable, max_iterations: int = 5,
                 step_max: float = 100.0, alf: float = 1e-4,
                 contraction: float = 0.5):
        self.loss_fn = loss_fn
        self.max_iterations = max_iterations
        self.step_max = step_max
        self.alf = alf
        self.contraction = contraction

    def optimize(self, x, f0: float, g, direction, initial_step: float = 1.0
                 ) -> float:
        """Returns the accepted step size along ``direction`` from ``x``."""
        slope = float(jnp.vdot(g, direction))
        if slope >= 0:
            return 0.0
        dnorm = float(jnp.linalg.norm(direction))
        step = min(initial_step, self.step_max / max(dnorm, 1e-12))
        for _ in range(self.max_iterations):
            f_new = float(self.loss_fn(x + step * direction))
            if f_new <= f0 + self.alf * step * slope:
                return step
            step *= self.contraction
        return step


# ------------------------------------------------------------------- optimizers
class BaseOptimizer:
    """Shared machinery (reference: BaseOptimizer.java): a jitted
    value-and-grad over the flat view + termination checks."""

    def __init__(self, max_iterations: int = 10,
                 terminations: Optional[list] = None,
                 line_search_iterations: int = 5):
        self.max_iterations = max_iterations
        self.terminations = terminations or [EpsTermination()]
        self.line_search_iterations = line_search_iterations

    def _setup(self, net, x, y):
        params0 = net.params
        flat0, unravel = ravel_pytree(params0)
        xj, yj = jnp.asarray(x), jnp.asarray(y)

        @jax.jit
        def value(flat):
            loss, _ = net._loss(unravel(flat), net.state, xj, yj, None, None,
                                train=False, rng=None)
            return loss

        @jax.jit
        def vg(flat):
            loss, g = jax.value_and_grad(value)(flat)
            # the loss stop_gradients the l1/l2 penalty; add its closed
            # form like the train step does (nn/regularization.py)
            from jax.flatten_util import ravel_pytree as _rp

            from deeplearning4j_tpu.nn.regularization import (
                add_regularization_grads,
            )

            params = unravel(flat)
            gtree = unravel(g)
            gtree = add_regularization_grads(net, params, gtree)
            return loss, _rp(gtree)[0]

        return flat0, unravel, value, vg

    def optimize(self, net, x, y) -> float:
        raise NotImplementedError

    def _finish(self, net, flat, unravel, loss):
        net.params = unravel(flat)
        net.score_value = float(loss)
        return float(loss)


class LineGradientDescent(BaseOptimizer):
    """Steepest descent + line search (reference: LineGradientDescent.java)."""

    def optimize(self, net, x, y) -> float:
        flat, unravel, value, vg = self._setup(net, x, y)
        loss, g = vg(flat)
        old = float("inf")
        for _ in range(self.max_iterations):
            direction = -g
            ls = BackTrackLineSearch(value, self.line_search_iterations)
            step = ls.optimize(flat, float(loss), g, direction)
            if step == 0.0:
                break
            flat = flat + step * direction
            old, loss = float(loss), None
            loss, g = vg(flat)
            if any(t.terminate(float(loss), old, jnp.linalg.norm(g))
                   for t in self.terminations):
                break
        return self._finish(net, flat, unravel, loss)


class ConjugateGradient(BaseOptimizer):
    """Nonlinear CG, Polak-Ribiere with automatic restart (reference:
    ConjugateGradient.java)."""

    def optimize(self, net, x, y) -> float:
        flat, unravel, value, vg = self._setup(net, x, y)
        loss, g = vg(flat)
        direction = -g
        old = float("inf")
        for _ in range(self.max_iterations):
            ls = BackTrackLineSearch(value, self.line_search_iterations)
            step = ls.optimize(flat, float(loss), g, direction)
            if step == 0.0:
                direction = -g  # restart
                step = ls.optimize(flat, float(loss), g, direction)
                if step == 0.0:
                    break
            flat = flat + step * direction
            old = float(loss)
            loss, g_new = vg(flat)
            # Polak-Ribiere beta, clamped at 0 (auto-restart)
            beta = float(jnp.vdot(g_new, g_new - g)
                         / jnp.maximum(jnp.vdot(g, g), 1e-12))
            beta = max(0.0, beta)
            direction = -g_new + beta * direction
            g = g_new
            if any(t.terminate(float(loss), old, jnp.linalg.norm(g))
                   for t in self.terminations):
                break
        return self._finish(net, flat, unravel, loss)


class LBFGS(BaseOptimizer):
    """Limited-memory BFGS, two-loop recursion (reference: LBFGS.java,
    default memory m=4... the reference uses 4; configurable here)."""

    def __init__(self, max_iterations: int = 10, memory: int = 10, **kw):
        super().__init__(max_iterations=max_iterations, **kw)
        self.memory = memory

    def optimize(self, net, x, y) -> float:
        flat, unravel, value, vg = self._setup(net, x, y)
        loss, g = vg(flat)
        s_hist: list = []
        y_hist: list = []
        old = float("inf")
        for _ in range(self.max_iterations):
            # two-loop recursion
            q = g
            alphas = []
            for s, yv in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / float(jnp.maximum(jnp.vdot(yv, s), 1e-12))
                a = rho * float(jnp.vdot(s, q))
                alphas.append((a, rho, s, yv))
                q = q - a * yv
            if y_hist:
                gamma = float(jnp.vdot(s_hist[-1], y_hist[-1])
                              / jnp.maximum(jnp.vdot(y_hist[-1],
                                                     y_hist[-1]), 1e-12))
                q = gamma * q
            for a, rho, s, yv in reversed(alphas):
                b = rho * float(jnp.vdot(yv, q))
                q = q + (a - b) * s
            direction = -q
            ls = BackTrackLineSearch(value, self.line_search_iterations)
            step = ls.optimize(flat, float(loss), g, direction)
            if step == 0.0:
                break
            new_flat = flat + step * direction
            old = float(loss)
            new_loss, new_g = vg(new_flat)
            s_hist.append(new_flat - flat)
            y_hist.append(new_g - g)
            if len(s_hist) > self.memory:
                s_hist.pop(0)
                y_hist.pop(0)
            flat, loss, g = new_flat, new_loss, new_g
            if any(t.terminate(float(loss), old, jnp.linalg.norm(g))
                   for t in self.terminations):
                break
        return self._finish(net, flat, unravel, loss)


_SOLVERS = {
    "line_gradient_descent": LineGradientDescent,
    "conjugate_gradient": ConjugateGradient,
    "lbfgs": LBFGS,
}


class Solver:
    """Facade choosing the optimizer from the configured algorithm
    (reference: optimize/Solver.java builder)."""

    def __init__(self, net, algorithm: Optional[str] = None,
                 max_iterations: int = 10, **kw):
        algo = (algorithm or getattr(net.conf, "optimization_algo",
                                     "stochastic_gradient_descent")).lower()
        if algo == "stochastic_gradient_descent":
            self.optimizer = None  # handled by the jitted fit path
        elif algo in _SOLVERS:
            self.optimizer = _SOLVERS[algo](max_iterations=max_iterations,
                                            **kw)
        else:
            raise ValueError(f"Unknown optimization algorithm '{algo}'")
        self.net = net

    def optimize(self, x, y) -> float:
        if self.optimizer is None:
            loss, _ = self.net.do_step(x, y)
            return loss
        return self.optimizer.optimize(self.net, x, y)
