"""Optimization: training listeners, solvers, gradient accumulation."""

from deeplearning4j_tpu.optimize.listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    CollectScoresIterationListener,
    EvaluativeListener,
    TimeIterationListener,
)
