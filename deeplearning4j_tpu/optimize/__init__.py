"""Optimization: training listeners (reference: optimize/listeners/) and
the numerical-health guard (optimize/health.py)."""

from deeplearning4j_tpu.optimize.listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    CollectScoresIterationListener,
    EvaluativeListener,
    TimeIterationListener,
    HealthListener,
)
from deeplearning4j_tpu.optimize.health import (
    DivergenceError,
    HealthPolicy,
)
from deeplearning4j_tpu.optimize.quantize import (
    confusion_delta,
    greedy_agreement,
    quantize_net,
    quantize_params,
)
