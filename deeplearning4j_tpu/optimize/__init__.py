"""Optimization: training listeners (reference: optimize/listeners/)."""

from deeplearning4j_tpu.optimize.listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    CollectScoresIterationListener,
    EvaluativeListener,
    TimeIterationListener,
)
