"""Shared shape-bucketing utilities for the jit-program caches.

Every jitted program in the stack is cached per input *shape signature*
(train steps in ``_step_cache``, inference/eval programs in
``_output_cache``, sharded forwards in ``ParallelInference._fwd_cache``).
Keying those caches on the EXACT batch size turns any ragged workload —
trailing partial batches, a serving frontend with arbitrary request sizes —
into a recompile-per-shape loop with unbounded cache growth. The fix is the
same pair everywhere:

- BUCKET the batch dimension: pad up to a canonical size (next power of
  two, optionally rounded to a worker-count multiple) by replicating the
  last row — real data, so no degenerate activations — and strip the pad
  rows from the result. Row-independent inference makes the real rows'
  outputs unchanged; eval paths additionally zero the pad rows' weights.
- BOUND the cache: an LRU so a long-lived server cannot hold compiled
  programs (and their device buffers) for every shape it has ever seen.

``FusedFitDriver`` keeps its own stream-bucket policy (first-batch size,
zeroed label-mask padding — see optimize/fused_fit.py); these helpers serve
the inference/eval family where requests arrive one at a time.
"""

from __future__ import annotations

from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

#: default LRU capacity for inference-program caches. Big enough that a
#: test suite or a bucketed serving workload never evicts (buckets are
#: O(log max_batch) per signature); small enough to bound a pathological
#: shape stream.
DEFAULT_CACHE_PROGRAMS = 64


def bucket_rows(n: int, multiple: int = 1) -> int:
    """Canonical padded batch size for ``n`` rows: the smallest power of two
    >= n, rounded up to a ``multiple`` (the mesh worker count, so a sharded
    batch still splits evenly). Distinct request sizes then collapse onto
    O(log max_batch) jit signatures instead of one per size."""
    if n < 1:
        raise ValueError(f"batch must have at least one row, got {n}")
    b = 1
    while b < n:
        b *= 2
    if multiple > 1 and b % multiple:
        b = -(-b // multiple) * multiple
    return b


def bucket_length(n: int, minimum: int = 8,
                  maximum: "int | None" = None) -> int:
    """Canonical padded TIME length for an ``n``-token sequence: the
    smallest power of two >= max(n, minimum), capped at ``maximum``.
    Prompt prefill keys its jit cache on this, so arbitrary prompt
    lengths collapse onto a handful of stable program shapes. ``minimum``
    stops one-token prompts from minting their own tiny buckets;
    ``maximum`` (the KV-cache capacity) is a hard bound — beyond it the
    sequence cannot fit at all."""
    if maximum is not None and n > maximum:
        raise ValueError(f"sequence of {n} tokens exceeds the maximum "
                         f"bucketed length {maximum}")
    b = bucket_rows(max(int(n), int(minimum)))
    if maximum is not None and b > maximum:
        b = int(maximum)
    return b


def bucket_pages(n: int, page_size: int,
                 maximum: "int | None" = None) -> int:
    """Number of fixed-size KV pages covering ``n`` tokens, rounded up to
    a power of two so paged-prefill programs compile per PAGE bucket
    rather than per pow2 TOKEN bucket (an 810-token and a 900-token
    prompt land on the same 64-page program when ``page_size=16``).
    ``maximum`` caps the bucket at a block table's page count; unlike
    ``bucket_length`` the cap is on pages, and ``n`` itself exceeding
    ``maximum * page_size`` tokens is the caller's admission error."""
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    if n < 1:
        raise ValueError(f"need at least one token, got {n}")
    pages = bucket_rows(-(-int(n) // int(page_size)))
    if maximum is not None:
        if n > maximum * page_size:
            raise ValueError(
                f"sequence of {n} tokens exceeds the page budget "
                f"{maximum} pages x {page_size}")
        if pages > maximum:
            pages = int(maximum)
    return pages


def pad_rows(a, target: int):
    """Pad ``a``'s leading dim up to ``target`` by replicating the last row
    (numpy in, numpy out; jax in, jax out — device arrays are padded on
    device, no host round-trip)."""
    pad = target - a.shape[0]
    if pad <= 0:
        return a
    xp = jnp if isinstance(a, jnp.ndarray) else np
    return xp.concatenate([a, xp.repeat(a[-1:], pad, axis=0)], axis=0)


class BoundedCache(OrderedDict):
    """dict-compatible LRU for jit-program caches: lookups refresh recency,
    inserts past ``maxsize`` evict the least-recently-used program."""

    def __init__(self, maxsize: int = DEFAULT_CACHE_PROGRAMS):
        super().__init__()
        if maxsize < 1:
            raise ValueError("maxsize must be >= 1")
        self.maxsize = maxsize

    def __getitem__(self, key):
        value = super().__getitem__(key)
        self.move_to_end(key)
        return value

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        while len(self) > self.maxsize:
            self.popitem(last=False)
