"""Gradient sharing with threshold-encoding compression.

Reference: optimize/solvers/accumulation/ — GradientsAccumulator SPI hooked
into the SGD step (StochasticGradientDescent.java:74), EncodingHandler.java:65
(``Nd4j.getExecutioner().thresholdEncode``: entries with |g| >= threshold are
quantised to sign(g)*threshold, the remainder stays in a residual buffer) and
:91 (broadcast of the sparse message).

TPU-native placement: over ICI, gradient reduction is a plain ``lax.psum``
inside the jitted step (bandwidth-rich — compression would cost more than it
saves; see parallel/trainer.py). This module provides the compression path
for bandwidth-POOR links (DCN / multi-pod, the reference's original setting):
jitted encode/decode + a residual-carrying accumulator whose quantised
all-reduce provably converges (error-feedback SGD).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def threshold_encode(grad, residual, threshold):
    """-> (quantised message, new residual).

    message = sign(g) * threshold where |g| >= threshold else 0, computed on
    g = grad + residual; new residual = g - message (error feedback). The
    dense message is exactly what the reference's sparse IntArray encodes —
    index/sign extraction is a transport detail (see ``sparsify``)."""
    g = grad + residual
    mask = jnp.abs(g) >= threshold
    msg = jnp.where(mask, jnp.sign(g) * threshold, 0.0)
    return msg, g - msg


def sparsify(message: np.ndarray, threshold: float):
    """Dense quantised message -> (int32 index array, sign bits) wire form
    (reference: the ND4J threshold-encoded IntArray layout in spirit)."""
    message = np.asarray(message).ravel()
    idx = np.nonzero(message)[0].astype(np.int32)
    signs = (message[idx] > 0)
    return idx, signs


def unsparsify(idx, signs, threshold: float, size: int) -> np.ndarray:
    out = np.zeros(size, np.float32)
    out[idx] = np.where(signs, threshold, -threshold)
    return out


class EncodingHandler:
    """Residual-carrying encoder for one worker (reference:
    EncodingHandler.java:65 — initialThreshold, with the adaptive shrink/grow
    of later reference versions omitted: fixed threshold, as at this
    vintage)."""

    def __init__(self, threshold: float = 1e-3):
        self.threshold = threshold
        self._residual = None

    def encode(self, flat_grad):
        g = jnp.asarray(flat_grad)
        if self._residual is None:
            self._residual = jnp.zeros_like(g)
        msg, self._residual = threshold_encode(g, self._residual,
                                               jnp.float32(self.threshold))
        return msg

    def residual_norm(self) -> float:
        return 0.0 if self._residual is None else \
            float(jnp.linalg.norm(self._residual))


class BasicGradientsAccumulator:
    """Multi-worker accumulator (reference: BasicGradientsAccumulator /
    LocalHandler): each worker stores (encoded) updates; ``get_update``
    returns the aggregated update for application. Synchronous semantics —
    the async Aeron transport is replaced by whatever carries the numpy
    arrays between hosts."""

    def __init__(self, workers: int, threshold: float = 1e-3,
                 compress: bool = True):
        self.workers = workers
        self.compress = compress
        self._handlers = [EncodingHandler(threshold)
                          for _ in range(workers)]
        self._pending: list = []

    def store_update(self, worker: int, flat_grad) -> None:
        if self.compress:
            msg = self._handlers[worker].encode(flat_grad)
        else:
            msg = jnp.asarray(flat_grad)
        self._pending.append(msg)

    def get_update(self):
        """Mean of stored updates; clears the round."""
        if not self._pending:
            return None
        out = self._pending[0]
        for m in self._pending[1:]:
            out = out + m
        out = out / float(len(self._pending))
        self._pending = []
        return out
