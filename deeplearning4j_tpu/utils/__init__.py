"""Utilities: JSON serde registry, pytree/flat-param helpers, model serialization."""
