"""Sharded training-state checkpoints via orbax.

Reference role: util/ModelSerializer.java (zip of config + coefficients +
updater state) — which utils/model_serializer.py ports faithfully. That
path gathers every array to one host process; for GSPMD-sharded training
(parallel/model_sharding.py, nlp/distributed.py) a [V, D] or multi-GB
parameter tree may not even fit one host. Orbax writes each shard from
the device that owns it and restores arrays WITH their shardings, so a
sharded training job resumes sharded.

Layout: ``<dir>/state`` (orbax pytree: params/updater_state/state +
iteration/epoch counters) + ``<dir>/configuration.json`` (same builder
JSON the zip format stores) — config stays human-readable, tensors stay
shard-parallel.
"""

from __future__ import annotations

import json
import os

import jax


def save_checkpoint(net, path: str) -> None:
    """Write a resumable checkpoint of ``net`` (MultiLayerNetwork or
    ComputationGraph) to directory ``path``."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    # multi-host: exactly one process writes the shared config file (the
    # tensor shards are per-process by construction, orbax coordinates
    # those itself)
    if jax.process_index() == 0:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "configuration.json"), "w",
                  encoding="utf-8") as f:
            f.write(net.conf.to_json())
    state = {
        "params": net.params,
        "updater_state": net.updater_state,
        "state": net.state,
        "counters": {"iteration": int(net.iteration),
                     "epoch": int(getattr(net, "epoch", 0))},
    }
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "state"), state, force=True)
        ckptr.wait_until_finished()


def load_checkpoint(path: str, net=None):
    """Restore from ``path``. With ``net`` given, its arrays' CURRENT
    shardings are the restore targets (a mesh-sharded net restores
    sharded, each host reading its shards); without, the net is rebuilt
    from configuration.json and restored unsharded."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if net is None:
        from deeplearning4j_tpu.utils import serde
        from deeplearning4j_tpu.utils.model_serializer import net_from_conf
        with open(os.path.join(path, "configuration.json"),
                  encoding="utf-8") as f:
            net = net_from_conf(serde.from_json(f.read()))
    target = {
        "params": net.params,
        "updater_state": net.updater_state,
        "state": net.state,
        "counters": {"iteration": 0, "epoch": 0},
    }
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                      target)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(path, "state"), abstract)
    net.params = state["params"]
    net.updater_state = state["updater_state"]
    net.state = state["state"]
    net.iteration = int(state["counters"]["iteration"])
    if hasattr(net, "epoch"):
        net.epoch = int(state["counters"]["epoch"])
    return net
