"""ModelGuesser: load a model file without knowing its format.

Reference: deeplearning4j-core util/ModelGuesser.java — tries
ModelSerializer restore, then Keras import, then normalizer loading, by
sniffing the file. Here detection is by magic bytes/structure, not by
trial-exception: zip (DL4J-format model archive), HDF5 (Keras), Google
word2vec binary / text word vectors.
"""

from __future__ import annotations

import os
import zipfile


def guess_format(path: str) -> str:
    """-> 'dl4j-zip' | 'keras-h5' | 'word2vec-binary' | 'word-vectors-text'
    (raises ValueError when none match)."""
    with open(path, "rb") as f:
        head = f.read(8)
    if head[:4] == b"PK\x03\x04" and zipfile.is_zipfile(path):
        return "dl4j-zip"
    if head == b"\x89HDF\r\n\x1a\n":
        return "keras-h5"
    # word2vec "V D\n" header: shared by the BINARY format (rows are
    # 'word ' + raw float32 bytes) and the gensim-style TEXT format (rows
    # are 'word 0.1 0.2 ...'). Disambiguate by whether the first row
    # parses as a text vector — misreading text as binary would
    # np.frombuffer UTF-8 digits into NaN-garbage floats with no error.
    try:
        with open(path, "rb") as f:
            header = f.readline(64)
            # unbounded: a capped readline would truncate wide text rows
            # (D >= ~450 at %.6f) and misroute them into the binary reader
            row = f.readline()
        parts = header.split()
        if len(parts) == 2 and all(p.isdigit() for p in parts):
            try:
                toks = row.decode("utf-8").split()
                if len(toks) == int(parts[1]) + 1:
                    [float(t) for t in toks[1:]]
                    return "word-vectors-text"
            except (UnicodeDecodeError, ValueError):
                pass
            return "word2vec-binary"
    except OSError:
        pass
    # text vectors: first line "word f f f ..."
    try:
        with open(path, encoding="utf-8") as f:
            first = f.readline().split()
        if len(first) >= 2:
            float(first[1])
            return "word-vectors-text"
    except (OSError, UnicodeDecodeError, ValueError):
        pass
    raise ValueError(f"Unrecognized model file: {path}")


def load_model_guess(path: str):
    """Load whatever ``path`` is (reference: ModelGuesser.loadModelGuess).
    Returns the loaded object: a network, or (words, vectors) for word
    vector formats."""
    kind = guess_format(path)
    if kind == "dl4j-zip":
        from deeplearning4j_tpu.utils.model_serializer import load_model
        return load_model(path)
    if kind == "keras-h5":
        from deeplearning4j_tpu.modelimport.keras import (
            import_keras_model_and_weights,
        )
        return import_keras_model_and_weights(path)
    if kind == "word2vec-binary":
        from deeplearning4j_tpu.nlp.serde import read_word2vec_binary
        return read_word2vec_binary(path)
    from deeplearning4j_tpu.nlp.serde import read_word_vectors_text
    return read_word_vectors_text(path)
