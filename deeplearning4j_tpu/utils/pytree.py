"""Flat-parameter-view helpers.

The reference keeps ALL network params in one contiguous buffer with per-layer views
(MultiLayerNetwork.java:103 flattenedParams, init :443-493) — that is what makes
parameter averaging and serialization one-array ops. Here the canonical form is the
pytree; these helpers provide the equivalent flat view with a deterministic order
(layer index, then the layer's param_order) for checkpoints and averaging parity.
"""

from __future__ import annotations

import numpy as np


def _ordered_items(layer_params: dict, layer):
    order = layer.param_order() if layer is not None else sorted(layer_params)
    for name in order:
        if name in layer_params:
            yield name, layer_params[name]


def flatten_params(params: dict, layers=None) -> np.ndarray:
    """params: {layer_key: {name: array}} -> 1-D float array."""
    chunks = []
    for i in sorted(params, key=lambda k: int(k)):
        layer = layers[int(i)] if layers is not None else None
        for _, v in _ordered_items(params[i], layer):
            chunks.append(np.asarray(v).ravel())
    if not chunks:
        return np.zeros((0,), np.float32)
    return np.concatenate(chunks)


def unflatten_params(flat, params_template: dict, layers=None) -> dict:
    """Inverse of flatten_params; shapes/dtypes come from the template pytree."""
    import jax.numpy as jnp

    flat = np.asarray(flat).ravel()
    out: dict = {}
    off = 0
    for i in sorted(params_template, key=lambda k: int(k)):
        layer = layers[int(i)] if layers is not None else None
        out[i] = dict(params_template[i])
        for name, v in _ordered_items(params_template[i], layer):
            n = int(np.prod(v.shape)) if v.shape else 1
            out[i][name] = jnp.asarray(
                flat[off:off + n].reshape(v.shape), dtype=v.dtype)
            off += n
    if off != flat.size:
        raise ValueError(f"Flat param size {flat.size} != expected {off}")
    return out


def run_fused_on_tpu(fn, *args):
    """Run ``fn(*args)`` jitted on TPU, eagerly elsewhere.

    Network param init is the user: per-layer eager sampling costs one XLA
    compile + one remote dispatch per distinct shape (84 s of ResNet50
    startup through the TPU tunnel, profiles/README.md), while one fused
    program compiles once; on CPU the relation inverts (tiny per-op
    programs are cached across architectures, a fused per-architecture
    compile is not). Values are bitwise identical either way."""
    import jax

    if jax.default_backend() == "tpu":
        fn = jax.jit(fn)
    return fn(*args)
