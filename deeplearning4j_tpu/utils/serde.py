"""JSON serde for config objects.

The reference serialises configs as Jackson polymorphic JSON with ``@class`` style
type tags (nn/conf/MultiLayerConfiguration.java ``toJson``/``fromJson``). We mirror
that contract: every config dataclass registers here and round-trips through plain
dicts tagged with ``"@class"``. Model zips then store ``configuration.json`` +
``coefficients.bin`` exactly like ModelSerializer (util/ModelSerializer.java:81-119).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

_CLASSES: dict[str, type] = {}


def register_serializable(cls):
    """Class decorator: register a dataclass for tagged JSON round-tripping."""
    _CLASSES[cls.__name__] = cls
    return cls


def to_jsonable(obj: Any) -> Any:
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.ops.losses import LossFunction

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, Activation):
        return {"@activation": obj.name}
    if isinstance(obj, LossFunction):
        return {"@loss": obj.name}
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): to_jsonable(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        d = {"@class": type(obj).__name__}
        for f in dataclasses.fields(obj):
            d[f.name] = to_jsonable(getattr(obj, f.name))
        return d
    if hasattr(obj, "tolist"):  # numpy / jax scalars & arrays
        return obj.tolist()
    raise TypeError(f"Cannot serialise {type(obj)!r} to JSON")


def _ensure_registry() -> None:
    """Import every module that defines @register_serializable classes, so
    deserialization works in a process that never imported them (e.g.
    ``load_model(path)`` as the very first call)."""
    import deeplearning4j_tpu.nn.conf.builders  # noqa: F401
    import deeplearning4j_tpu.nn.conf.graph_conf  # noqa: F401
    import deeplearning4j_tpu.nn.conf.layers  # noqa: F401
    import deeplearning4j_tpu.nn.conf.layers.attention  # noqa: F401
    import deeplearning4j_tpu.nn.conf.preprocessors  # noqa: F401
    import deeplearning4j_tpu.nn.transferlearning  # noqa: F401
    import deeplearning4j_tpu.nn.updater  # noqa: F401


def from_jsonable(d: Any) -> Any:
    from deeplearning4j_tpu.ops.activations import get_activation
    from deeplearning4j_tpu.ops.losses import get_loss

    if isinstance(d, list):
        return [from_jsonable(x) for x in d]
    if isinstance(d, dict):
        if "@activation" in d:
            return get_activation(d["@activation"])
        if "@loss" in d:
            return get_loss(d["@loss"])
        if "@class" in d:
            name = d["@class"]
            if name not in _CLASSES:
                # registrations happen at class definition; in a fresh
                # process that only imported the loader, the defining
                # modules may not be loaded yet — pull them in once
                _ensure_registry()
            if name not in _CLASSES:
                raise ValueError(f"Unknown config class '{name}' in JSON")
            cls = _CLASSES[name]
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {k: from_jsonable(v) for k, v in d.items()
                      if k != "@class" and k in field_names}
            obj = cls(**kwargs)
            return obj
        return {k: from_jsonable(v) for k, v in d.items()}
    return d


def to_json(obj: Any, indent=2) -> str:
    return json.dumps(to_jsonable(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_jsonable(json.loads(s))
