"""Model serialization: zip of configuration.json + coefficients.bin (+ updater state).

Reference: util/ModelSerializer.java:40,52-119 (writeModel: zip entries
``configuration.json``, ``coefficients.bin``, ``updaterState.bin``; restore
:137-148). We keep the same zip layout and entry names so checkpoints are
layout-compatible in spirit; coefficients are the flat param view in layer/param
order (float32 little-endian), extra state (BN running stats, updater slots) goes in
npz entries.
"""

from __future__ import annotations

import io
import json
import zipfile

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.utils import serde


def _state_to_npz(tree) -> bytes:
    """Flatten a nested dict-of-arrays to npz with '/'-joined keys."""
    flat = {}

    def rec(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                rec(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node)

    rec("", tree)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    return buf.getvalue()


def _npz_to_state(data: bytes) -> dict:
    out: dict = {}
    with np.load(io.BytesIO(data)) as npz:
        for key in npz.files:
            node = out
            parts = key.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = jnp.asarray(npz[key])
    return out


def _merge_into(template, loaded):
    """Recursively overlay loaded leaves onto a freshly-initialised template.

    npz flattening cannot represent *empty* dicts (paramless vertices/layers),
    so a plain reload would change the pytree structure; overlaying onto the
    template preserves it."""
    if not isinstance(template, dict):
        return loaded if loaded is not None else template
    out = {}
    for k, v in template.items():
        out[k] = _merge_into(v, loaded.get(k) if isinstance(loaded, dict)
                             else None)
    return out


def save_model(net, path: str, save_updater: bool = True) -> None:
    """Write a MultiLayerNetwork/ComputationGraph to a DL4J-style model zip."""
    conf_json = net.conf.to_json()
    flat = net.params_flat()
    meta = {
        "format_version": 1,
        "model_type": type(net).__name__,
        "iteration": net.iteration,
        "epoch": getattr(net, "epoch", 0),
        "num_params": int(flat.size),
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", conf_json)
        zf.writestr("coefficients.bin", flat.astype("<f4").tobytes())
        zf.writestr("metadata.json", json.dumps(meta))
        zf.writestr("state.npz", _state_to_npz(net.state))
        if save_updater and net.updater_state:
            zf.writestr("updaterState.bin", _state_to_npz(net.updater_state))


def net_from_conf(conf):
    """Build + init the right network class for a deserialized config —
    the ONE dispatch shared by every loader (zip, orbax)."""
    if hasattr(conf, "vertices"):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph(conf).init()
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    if hasattr(conf, "preprocessors"):
        conf.preprocessors = {int(k): v
                              for k, v in conf.preprocessors.items()}
    return MultiLayerNetwork(conf).init()


def load_model(path: str, load_updater: bool = True):
    """Restore a model zip -> initialised network with params/state/updater."""
    with zipfile.ZipFile(path, "r") as zf:
        conf = serde.from_json(zf.read("configuration.json").decode())
        meta = json.loads(zf.read("metadata.json").decode())
        coeff = np.frombuffer(zf.read("coefficients.bin"), "<f4").copy()
        state = _npz_to_state(zf.read("state.npz")) if "state.npz" in zf.namelist() else {}
        upd = (_npz_to_state(zf.read("updaterState.bin"))
               if load_updater and "updaterState.bin" in zf.namelist() else None)

    net = net_from_conf(conf)
    net.set_params_flat(coeff)
    if state:
        net.state = _merge_into(net.state, state)
    if upd is not None:
        net.updater_state = _merge_into(net.updater_state, upd)
    net.iteration = meta.get("iteration", 0)
    net.epoch = meta.get("epoch", 0)
    return net
