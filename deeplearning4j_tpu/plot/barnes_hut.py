"""Barnes-Hut t-SNE (reference: plot/BarnesHutTsne.java:65 + the SPTree in
clustering/sptree/SpTree.java).

TPU-native redesign of the tree: the reference walks a pointer-based
quadtree per point per iteration (SpTree.computeNonEdgeForces) — adaptive,
sequential, unvectorizable. Here the SAME far-field approximation (a distant
cell of points acts through its centroid, opening criterion s/d < theta) is
expressed as a fixed MULTIRESOLUTION GRID LADDER with FMM-style interaction
lists:

- levels l0..L of 2^l x 2^l grids over the embedding bbox; per level, cell
  counts and centroid sums are one scatter-add;
- a point interacts with the cells of level l that lie in the refinement
  ring of its parent cell's near region (children of the parent's
  (2R+1)^2 neighborhood minus its own (2R+1)^2 neighborhood, R = ceil(1/theta)
  — exactly the cells whose size/distance ratio first satisfies the opening
  criterion at this level);
- at the finest level the near region is taken at cell granularity, with L
  chosen so cells hold ~1 point (the centroid of a 1-point cell IS the
  point, so the near field is near-exact).

Every cell of the finest partition is counted exactly once across the
ladder. All shapes are static: the whole gradient step jits to gathers,
scatter-adds and elementwise math — no pointers, no recursion.

The attractive (kNN) term uses the standard sparse symmetrized-P edge list
(reference computeGaussianPerplexity with its VPTree kNN; here kNN is
chunked matmul + top_k on device).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------------ kNN + P
def _knn(x, k: int, chunk: int = 1024):
    """k nearest neighbors by chunked device matmul + top_k.
    Returns (idx [N,k], d2 [N,k]) excluding self."""
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    sq = jnp.sum(x * x, axis=1)

    @jax.jit
    def one_chunk(xc, sqc):
        d2 = sqc[:, None] - 2.0 * (xc @ x.T) + sq[None, :]
        neg, idx = jax.lax.top_k(-d2, k + 1)
        return idx, -neg

    idxs, d2s = [], []
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        idx, d2 = one_chunk(x[s:e], sq[s:e])
        idxs.append(np.asarray(idx))
        d2s.append(np.asarray(d2))
    idx = np.concatenate(idxs)
    d2 = np.concatenate(d2s)
    # drop self (it is the 0-distance hit; fall back to dropping the last
    # column for rows where numerical noise hid it)
    rows = np.arange(n)[:, None]
    degenerate = ~np.any(idx == rows, axis=1)
    self_pos = np.argmax(idx == rows, axis=1)
    # degenerate rows (duplicates/ties hid the self-hit): drop the FARTHEST
    # candidate, keeping the true nearest neighbor in column 0
    self_pos[degenerate] = idx.shape[1] - 1
    keep = np.ones_like(idx, bool)
    keep[np.arange(n), self_pos] = False
    idx = idx[keep].reshape(n, k)
    d2 = np.maximum(d2[keep].reshape(n, k), 0.0)
    return idx, d2


def _perplexity_search(d2: np.ndarray, perplexity: float, tol=1e-5,
                      max_tries=50) -> np.ndarray:
    """Vectorized per-row precision search on the kNN distances (same
    bisection as BarnesHutTsne.computeGaussianPerplexity, all rows at
    once). Returns conditional probabilities [N, k]."""
    n = d2.shape[0]
    target = np.log(perplexity)
    beta = np.ones(n)
    lo = np.full(n, -np.inf)
    hi = np.full(n, np.inf)
    p = np.zeros_like(d2)
    for _ in range(max_tries):
        p = np.exp(-d2 * beta[:, None])
        sum_p = np.maximum(p.sum(axis=1), 1e-12)
        h = np.log(sum_p) + beta * (d2 * p).sum(axis=1) / sum_p
        diff = h - target
        done = np.abs(diff) < tol
        if done.all():
            break
        too_high = diff > 0
        lo = np.where(too_high & ~done, beta, lo)
        hi = np.where(~too_high & ~done, beta, hi)
        beta = np.where(
            too_high & ~done,
            np.where(np.isinf(hi), beta * 2.0, (beta + hi) / 2.0),
            np.where(~done,
                     np.where(np.isneginf(lo), beta / 2.0, (beta + lo) / 2.0),
                     beta))
    return p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)


def build_sparse_p(x, perplexity: float, k: int | None = None):
    """Symmetrized sparse input similarities as a COO edge list
    (edges_i, edges_j, edges_p), each [2*N*k]. Sum of edges_p == 1."""
    x = np.asarray(x, np.float32)
    n = x.shape[0]
    if k is None:
        k = min(n - 1, int(3 * perplexity))
    idx, d2 = _knn(x, k)
    cond_p = _perplexity_search(d2, min(perplexity, (n - 1) / 3.0))
    rows = np.repeat(np.arange(n, dtype=np.int32), k)
    cols = idx.astype(np.int32).ravel()
    vals = cond_p.ravel()
    # symmetrize: P = (P + P^T) / 2N over the union graph == concatenating
    # each directed edge and its reverse at half weight
    ei = np.concatenate([rows, cols])
    ej = np.concatenate([cols, rows])
    ep = np.concatenate([vals, vals]) / (2.0 * n)
    ep = ep / max(ep.sum(), 1e-12)
    return ei, ej, ep


# -------------------------------------------------------------- BH ladder
def _ladder_config(n: int, theta: float):
    """Static level plan. R = ceil(1/theta) cells is the near radius the
    opening criterion s/d < theta demands; L makes finest cells ~1 point."""
    R = int(min(4, max(1, np.ceil(1.0 / max(theta, 0.25)))))
    l0 = int(np.ceil(np.log2(2 * R + 2)))          # coarsest useful grid
    L = max(l0, int(np.ceil(np.log(max(n, 4)) / np.log(4))) + 1)
    return R, l0, L


def _bh_repulsion(y, *, R: int, l0: int, L: int):
    """Repulsive numerator forces and partition Z via the grid ladder.
    y: [N, 2]. Returns (rep [N,2] = sum n_c k^2 (y - mu_c), z [N])."""
    n = y.shape[0]
    lo = jnp.min(y, axis=0)
    span = jnp.maximum(jnp.max(jnp.max(y, axis=0) - lo), 1e-9)
    y01 = (y - lo) / span * (1.0 - 1e-6)

    rep = jnp.zeros_like(y)
    z = jnp.zeros((n,), y.dtype)

    def cell_tables(level):
        G = 1 << level
        ci = jnp.clip((y01 * G).astype(jnp.int32), 0, G - 1)   # [N, 2]
        flat = ci[:, 0] * G + ci[:, 1]
        cnt = jnp.zeros((G * G,), y.dtype).at[flat].add(1.0)
        sums = jnp.zeros((G * G, 2), y.dtype).at[flat].add(y)
        return G, ci, cnt, sums

    def interact(cnt, sums, G, cells):
        """cells: [N, M, 2] int32 candidate cells (may be masked with -1)."""
        valid = ((cells[..., 0] >= 0) & (cells[..., 0] < G)
                 & (cells[..., 1] >= 0) & (cells[..., 1] < G))
        flat = jnp.clip(cells[..., 0] * G + cells[..., 1], 0, G * G - 1)
        n_c = jnp.where(valid, cnt[flat], 0.0)                 # [N, M]
        mu = sums[flat] / jnp.maximum(n_c, 1.0)[..., None]     # [N, M, 2]
        diff = y[:, None, :] - mu
        kq = 1.0 / (1.0 + jnp.sum(diff * diff, axis=-1))       # [N, M]
        kq = jnp.where(n_c > 0, kq, 0.0)
        return (jnp.sum((n_c * kq * kq)[..., None] * diff, axis=1),
                jnp.sum(n_c * kq, axis=1))

    # refinement block edge: the parent's near region is (2R+1) cells per
    # dim, whose children span 2*(2R+1) cells starting at 2*(parent - R)
    side = 2 * (2 * R + 1)
    for level in range(l0, L + 1):
        G, ci, cnt, sums = cell_tables(level)
        if level == l0:
            # all cells of the coarsest grid beyond the near region
            gx, gy = jnp.meshgrid(jnp.arange(G), jnp.arange(G),
                                  indexing="ij")
            allc = jnp.stack([gx.ravel(), gy.ravel()], -1)     # [G*G, 2]
            cells = jnp.broadcast_to(allc[None], (n, G * G, 2))
            near = (jnp.max(jnp.abs(cells - ci[:, None, :]), axis=-1) <= R)
            cells = jnp.where(near[..., None], -1, cells)
        else:
            # children of the parent's near region, minus own near region
            base = 2 * ((ci >> 1) - R)                          # [N, 2]
            off = jnp.stack(jnp.meshgrid(jnp.arange(side),
                                         jnp.arange(side),
                                         indexing="ij"), -1).reshape(-1, 2)
            cells = base[:, None, :] + off[None, :, :]          # [N, s^2, 2]
            near = (jnp.max(jnp.abs(cells - ci[:, None, :]), axis=-1) <= R)
            cells = jnp.where(near[..., None], -1, cells)
        r_l, z_l = interact(cnt, sums, G, cells)
        rep = rep + r_l
        z = z + z_l
        if level == L:
            # near region at the finest level, at cell granularity (cells
            # hold ~1 point); subtract the self pair (num_ii = 1, force 0)
            off = jnp.stack(jnp.meshgrid(jnp.arange(-R, R + 1),
                                         jnp.arange(-R, R + 1),
                                         indexing="ij"), -1).reshape(-1, 2)
            cells = ci[:, None, :] + off[None, :, :]
            r_l, z_l = interact(cnt, sums, G, cells)
            rep = rep + r_l
            z = z + z_l - 1.0
    return rep, z


def make_bh_step(n: int, theta: float):
    """Build the jitted BH gradient step for a fixed N/theta."""
    R, l0, L = _ladder_config(n, theta)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(y, gains, inc, ei, ej, ep, momentum, lr):
        yi = y[ei]
        yj = y[ej]
        diff = yi - yj
        num = 1.0 / (1.0 + jnp.sum(diff * diff, axis=-1))
        attr = jnp.zeros_like(y).at[ei].add(
            (ep * num)[:, None] * diff)                        # [N, 2]
        rep, z = _bh_repulsion(y, R=R, l0=l0, L=L)
        zsum = jnp.maximum(jnp.sum(z), 1e-12)
        grad = 4.0 * (attr - rep / zsum)
        gains = jnp.where(jnp.sign(grad) != jnp.sign(inc),
                          gains + 0.2, gains * 0.8)
        gains = jnp.maximum(gains, 0.01)
        inc = momentum * inc - lr * gains * grad
        y = y + inc
        y = y - jnp.mean(y, axis=0)
        # sparse-P KL estimate (reference reports the same edge sum)
        q = jnp.maximum(num / zsum, 1e-12)
        kl = jnp.sum(ep * jnp.log(jnp.maximum(ep, 1e-12) / q))
        return y, gains, inc, kl

    return step


class BarnesHutTsne:
    """reference: plot/BarnesHutTsne.java:65 — same knobs/surface as Tsne,
    with the grid-ladder BH gradient (theta honored) and sparse kNN input
    similarities, so reference-scale N (~100k words) embeds in minutes."""

    def __init__(self, num_dimension: int = 2, perplexity: float = 30.0,
                 theta: float = 0.5, learning_rate: float = 200.0,
                 max_iter: int = 500, momentum: float = 0.5,
                 final_momentum: float = 0.8, switch_momentum_iter: int = 250,
                 stop_lying_iter: int = 100, exaggeration: float = 12.0,
                 seed: int = 42):
        if num_dimension != 2:
            raise ValueError("BarnesHutTsne embeds to 2 dimensions (the "
                             "reference's quadtree is 2-D too); use Tsne "
                             "for other target dims")
        self.num_dimension = num_dimension
        self.perplexity = perplexity
        self.theta = theta
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iter = switch_momentum_iter
        self.stop_lying_iter = stop_lying_iter
        self.exaggeration = exaggeration
        self.seed = seed
        self.y: np.ndarray = None
        self.kl: float = float("nan")

    def fit(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        ei, ej, ep = build_sparse_p(x, self.perplexity)
        ei = jnp.asarray(ei)
        ej = jnp.asarray(ej)
        ep_plain = jnp.asarray(ep, jnp.float32)
        ep_lying = ep_plain * self.exaggeration
        step = make_bh_step(n, self.theta)

        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.normal(0, 1e-4, (n, 2)), jnp.float32)
        gains = jnp.ones_like(y)
        inc = jnp.zeros_like(y)
        kl = jnp.inf
        for it in range(self.max_iter):
            mom = self.momentum if it < self.switch_momentum_iter \
                else self.final_momentum
            p_cur = ep_lying if it < self.stop_lying_iter else ep_plain
            y, gains, inc, kl = step(y, gains, inc, ei, ej, p_cur,
                                     jnp.float32(mom),
                                     jnp.float32(self.learning_rate))
        self.y = np.asarray(y)
        self.kl = float(kl)
        return self.y

    def get_y(self) -> np.ndarray:
        return self.y
