"""t-SNE embedding (reference: deeplearning4j-core plot/BarnesHutTsne.java:65
— Barnes-Hut approximated gradients over an SPTree, theta=0.5).

TPU-native divergence: the Barnes-Hut quadtree is a pointer-chasing CPU
structure; on TPU the EXACT O(N^2) gradient is a pair of [N, N] matmul/
softmax-like programs that the MXU eats for the N <= ~20k regime t-SNE is
used in. So this implements exact t-SNE with the same knobs (perplexity,
theta accepted-but-ignored, learning rate, momentum schedule, early
exaggeration) and the same ``fit / get_y`` surface as the reference.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _hbeta(d_row, beta):
    p = np.exp(-d_row * beta)
    sum_p = max(p.sum(), 1e-12)
    h = np.log(sum_p) + beta * float((d_row * p).sum()) / sum_p
    return h, p / sum_p


def _binary_search_p(d2: np.ndarray, perplexity: float, tol=1e-5,
                     max_tries=50) -> np.ndarray:
    """Per-row precision search for target perplexity (reference:
    BarnesHutTsne.computeGaussianPerplexity)."""
    n = d2.shape[0]
    target = np.log(perplexity)
    P = np.zeros((n, n))
    for i in range(n):
        idx = np.arange(n) != i
        beta, lo, hi = 1.0, -np.inf, np.inf
        row = d2[i, idx]
        for _ in range(max_tries):
            h, p = _hbeta(row, beta)
            diff = h - target
            if abs(diff) < tol:
                break
            if diff > 0:
                lo = beta
                beta = beta * 2 if hi == np.inf else (beta + hi) / 2
            else:
                hi = beta
                beta = beta / 2 if lo == -np.inf else (beta + lo) / 2
        P[i, idx] = p
    return P


@partial(jax.jit, static_argnames=())
def _tsne_step(y, P, gains, inc, momentum, lr):
    """One exact t-SNE gradient step: Q from pairwise distances, gradient
    4(P-Q)(y_i-y_j)q_ij, with gains + momentum (reference: gradient loop in
    BarnesHutTsne.step)."""
    n = y.shape[0]
    sum_y = jnp.sum(y * y, axis=1)
    num = 1.0 / (1.0 + sum_y[:, None] - 2.0 * (y @ y.T) + sum_y[None, :])
    num = num * (1.0 - jnp.eye(n, dtype=y.dtype))
    Q = num / jnp.maximum(jnp.sum(num), 1e-12)
    PQ = (P - jnp.maximum(Q, 1e-12)) * num  # [N, N]
    grad = 4.0 * (jnp.diag(PQ.sum(axis=1)) - PQ) @ y
    gains = jnp.where(jnp.sign(grad) != jnp.sign(inc),
                      gains + 0.2, gains * 0.8)
    gains = jnp.maximum(gains, 0.01)
    inc = momentum * inc - lr * gains * grad
    y = y + inc
    y = y - jnp.mean(y, axis=0)
    kl = jnp.sum(P * jnp.log(jnp.maximum(P, 1e-12)
                             / jnp.maximum(Q, 1e-12)))
    return y, gains, inc, kl


class Tsne:
    """reference: plot/BarnesHutTsne.java:65 builder (numDimension,
    perplexity, theta, learningRate, setMaxIter)."""

    def __init__(self, num_dimension: int = 2, perplexity: float = 30.0,
                 theta: float = 0.5, learning_rate: float = 200.0,
                 max_iter: int = 500, momentum: float = 0.5,
                 final_momentum: float = 0.8, switch_momentum_iter: int = 250,
                 stop_lying_iter: int = 100, exaggeration: float = 12.0,
                 seed: int = 42):
        self.num_dimension = num_dimension
        self.perplexity = perplexity
        self.theta = theta  # accepted for API parity; exact gradient used
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iter = switch_momentum_iter
        self.stop_lying_iter = stop_lying_iter
        self.exaggeration = exaggeration
        self.seed = seed
        self.y: np.ndarray = None
        self.kl: float = float("nan")

    def fit(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        P = _binary_search_p(d2, min(self.perplexity, (n - 1) / 3.0))
        P = (P + P.T) / (2.0 * n)
        P = np.maximum(P / P.sum(), 1e-12)

        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.normal(0, 1e-4, (n, self.num_dimension)),
                        jnp.float32)
        gains = jnp.ones_like(y)
        inc = jnp.zeros_like(y)
        P_dev = jnp.asarray(P * self.exaggeration, jnp.float32)
        P_plain = jnp.asarray(P, jnp.float32)
        kl = jnp.inf
        for it in range(self.max_iter):
            mom = self.momentum if it < self.switch_momentum_iter \
                else self.final_momentum
            Pcur = P_dev if it < self.stop_lying_iter else P_plain
            y, gains, inc, kl = _tsne_step(
                y, Pcur, gains, inc, jnp.float32(mom),
                jnp.float32(self.learning_rate))
        self.y = np.asarray(y)
        self.kl = float(kl)
        return self.y

    def get_y(self) -> np.ndarray:
        return self.y
