"""Dimensionality-reduction plotting tools (reference: deeplearning4j-core
plot/ — BarnesHutTsne.java:65)."""

from deeplearning4j_tpu.plot.tsne import Tsne

BarnesHutTsne = Tsne  # reference-name alias

__all__ = ["Tsne", "BarnesHutTsne"]
