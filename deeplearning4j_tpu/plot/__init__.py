"""Dimensionality-reduction plotting tools (reference: deeplearning4j-core
plot/ — BarnesHutTsne.java:65).

``Tsne`` is the exact O(N^2) device implementation (the MXU eats it for
N <= ~5k); ``BarnesHutTsne`` is the grid-ladder Barnes-Hut implementation
(sparse kNN attraction + FMM-style far-field, O(N log N)-class) for
reference-scale N."""

from deeplearning4j_tpu.plot.barnes_hut import BarnesHutTsne
from deeplearning4j_tpu.plot.tsne import Tsne

__all__ = ["Tsne", "BarnesHutTsne"]
