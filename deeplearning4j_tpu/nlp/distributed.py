"""Distributed embedding training: vocab-row sharding over a device mesh.

Reference: dl4j-spark-nlp
spark/models/embeddings/word2vec/Word2Vec.java:136-187 — Spark trains
word2vec per-partition and AVERAGES word vectors across the cluster every
epoch (an approximation that degrades with partition count).

TPU-native redesign: no parameter averaging at all. The lookup tables
themselves are SHARDED by vocabulary row over the mesh's model axis
(``NamedSharding(P("model", None))``) and the SAME jitted epoch programs
(``skipgram_corpus_epoch`` / ``cbow_corpus_epoch`` / ``dbow_corpus_epoch``)
run under GSPMD, which partitions the row gathers / segment sums / scatters
and inserts the collectives over ICI. Because it is the identical program,
results are bit-identical to single-device training up to float reduction
order (parity-tested on the virtual CPU mesh) — exact where the Spark
path is approximate, and the [V, D] tables scale past one device's HBM
(the reason the reference had to distribute in the first place).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS

__all__ = ["shard_embedding_tables", "sharded_vocab_rows"]


def sharded_vocab_rows(num_words: int, mesh: Mesh) -> int:
    """Table row count after padding to the model-axis size (padded rows
    are never indexed: all vocab ids, huffman points and negative-table
    entries are < num_words)."""
    m = mesh.shape[MODEL_AXIS]
    return ((num_words + m - 1) // m) * m


def shard_embedding_tables(model, mesh: Mesh):
    """Place ``model``'s syn0 / syn1 / syn1neg row-sharded over ``mesh``'s
    model axis (rows padded up to a multiple of the axis size). Subsequent
    ``fit`` calls run the usual epoch programs: jit sees sharded donated
    inputs, GSPMD partitions the program, and the tables stay sharded
    across epochs. Works for Word2Vec / SequenceVectors (cbow) /
    ParagraphVectors alike — they share the table layout.

    Call after ``build_vocab``/``reset_weights`` (or after a prior fit —
    resharding existing tables is fine)."""
    if model.syn0 is None:
        model.reset_weights()
    sh = NamedSharding(mesh, P(MODEL_AXIS, None))

    def place(t):
        # each table pads to its own multiple of the axis size (syn1 is a
        # [1, D] dummy when hierarchical softmax is off)
        pad = sharded_vocab_rows(t.shape[0], mesh) - t.shape[0]
        if pad:
            t = jnp.concatenate(
                [t, jnp.zeros((pad, t.shape[1]), t.dtype)])
        return jax.device_put(t, sh)

    model.syn0 = place(model.syn0)
    model.syn1 = place(model.syn1)
    model.syn1neg = place(model.syn1neg)
    return model
