"""GloVe: co-occurrence weighted least squares (reference:
models/glove/Glove.java:~60 builder, GloveWeightLookupTable AdaGrad update;
Pennington et al. 2014).

TPU-native: the co-occurrence counts accumulate in a host dict (sparse,
data-dependent — wrong shape for XLA), then training runs as jitted AdaGrad
batches over the nonzero (i, j, X_ij) triples: cost term
f(X) * (w_i . w~_j + b_i + b~_j - log X)^2 with f(x) = (x/x_max)^alpha
capped at 1.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


@partial(jax.jit, static_argnames=("batch",),
         donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_epoch(W, Wc, b, bc, hW, hWc, hb, hbc, wi, wj, logx, fx, key,
                 lr, eps, *, batch: int):
    """One whole epoch as a single device program: shuffle the nonzero
    triples with the on-device PRNG, then `lax.scan` AdaGrad batches —
    one dispatch per EPOCH instead of one per batch (the same
    dispatch-granularity change that made skipgram fast; padding triples
    carry fx=0 so they contribute exactly nothing)."""
    n = wi.shape[0]
    perm = jax.random.permutation(key, n)
    nb = n // batch

    def gather(a):
        return a[perm].reshape(nb, batch, *a.shape[1:])

    xs = (gather(wi), gather(wj), gather(logx), gather(fx))

    def body(carry, inp):
        W, Wc, b, bc, hW, hWc, hb, hbc = carry
        bwi, bwj, blogx, bfx = inp
        out = _glove_batch(W, Wc, b, bc, hW, hWc, hb, hbc, bwi, bwj,
                           blogx, bfx, lr, eps)
        return out, 0

    carry, _ = jax.lax.scan(body, (W, Wc, b, bc, hW, hWc, hb, hbc), xs)
    return carry


def _glove_batch(W, Wc, b, bc, hW, hWc, hb, hbc, wi, wj, logx, fx, lr, eps):
    """One AdaGrad batch over triples (wi, wj, X)."""
    vi = W[wi]      # [B, D]
    vj = Wc[wj]
    diff = jnp.einsum("bd,bd->b", vi, vj) + b[wi] + bc[wj] - logx  # [B]
    g = fx * diff  # [B]
    gi = g[:, None] * vj
    gj = g[:, None] * vi
    gb = g
    # AdaGrad accumulators
    hW = hW.at[wi].add(gi * gi)
    hWc = hWc.at[wj].add(gj * gj)
    hb = hb.at[wi].add(gb * gb)
    hbc = hbc.at[wj].add(gb * gb)
    W = W.at[wi].add(-lr * gi / jnp.sqrt(hW[wi] + eps))
    Wc = Wc.at[wj].add(-lr * gj / jnp.sqrt(hWc[wj] + eps))
    b = b.at[wi].add(-lr * gb / jnp.sqrt(hb[wi] + eps))
    bc = bc.at[wj].add(-lr * gb / jnp.sqrt(hbc[wj] + eps))
    return W, Wc, b, bc, hW, hWc, hb, hbc


class Glove:
    def __init__(self, layer_size: int = 100, window: int = 15,
                 min_word_frequency: int = 1, epochs: int = 25,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 4096,
                 symmetric: bool = True, seed: int = 12345,
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.symmetric = symmetric
        self.seed = seed
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory()
        self.vocab = None
        self.syn0 = None

    def _cooccurrences(self, sentences) -> dict:
        """Distance-weighted co-occurrence counts (reference:
        glove/count/CoOccurrenceCounter; weight 1/d)."""
        counts: dict = {}
        for sentence in sentences:
            toks = self.tokenizer_factory.create(sentence).tokens() \
                if isinstance(sentence, str) else list(sentence)
            idx = [self.vocab.index_of(t) for t in toks]
            idx = [i for i in idx if i >= 0]
            for i, wi in enumerate(idx):
                for d in range(1, self.window + 1):
                    j = i + d
                    if j >= len(idx):
                        break
                    wj = idx[j]
                    w = 1.0 / d
                    counts[(wi, wj)] = counts.get((wi, wj), 0.0) + w
                    if self.symmetric:
                        counts[(wj, wi)] = counts.get((wj, wi), 0.0) + w
        return counts

    def fit(self, sentences) -> "Glove":
        if self.vocab is None:
            self.vocab = VocabConstructor(
                min_word_frequency=self.min_word_frequency,
                tokenizer_factory=self.tokenizer_factory,
                build_huffman=False).build_vocab(
                    s if isinstance(s, str) else " ".join(s)
                    for s in sentences)
        if hasattr(sentences, "reset"):
            sentences.reset()
        cooc = self._cooccurrences(sentences)
        if not cooc:
            raise ValueError("Empty co-occurrence matrix")
        V, D = self.vocab.num_words(), self.layer_size
        rng = np.random.RandomState(self.seed)
        W = jnp.asarray((rng.random_sample((V, D)) - 0.5) / D, jnp.float32)
        Wc = jnp.asarray((rng.random_sample((V, D)) - 0.5) / D, jnp.float32)
        b = jnp.zeros(V, jnp.float32)
        bc = jnp.zeros(V, jnp.float32)
        hW = jnp.full((V, D), 1e-8, jnp.float32)
        hWc = jnp.full((V, D), 1e-8, jnp.float32)
        hb = jnp.full(V, 1e-8, jnp.float32)
        hbc = jnp.full(V, 1e-8, jnp.float32)

        keys = np.array(list(cooc.keys()), np.int32)
        vals = np.array(list(cooc.values()), np.float32)
        logx = np.log(vals)
        fx = np.minimum((vals / self.x_max) ** self.alpha, 1.0) \
            .astype(np.float32)
        n = keys.shape[0]
        B = min(self.batch_size, n)
        # pad the triple list to a whole number of batches; fx=0 padding
        # contributes zero gradient and zero AdaGrad accumulation
        pad = (-n) % B
        wi = jnp.asarray(np.concatenate([keys[:, 0],
                                         np.zeros(pad, np.int32)]))
        wj = jnp.asarray(np.concatenate([keys[:, 1],
                                         np.zeros(pad, np.int32)]))
        logx_d = jnp.asarray(np.concatenate([logx,
                                             np.zeros(pad, np.float32)]))
        fx_d = jnp.asarray(np.concatenate([fx, np.zeros(pad, np.float32)]))
        for e in range(self.epochs):
            (W, Wc, b, bc, hW, hWc, hb, hbc) = _glove_epoch(
                W, Wc, b, bc, hW, hWc, hb, hbc, wi, wj, logx_d, fx_d,
                jax.random.fold_in(jax.random.PRNGKey(self.seed), e),
                jnp.float32(self.learning_rate), jnp.float32(1e-8),
                batch=B)
        # final embedding = W + Wc (standard GloVe practice)
        self.syn0 = W + Wc
        return self

    # --------------------------------------------------------------- queries
    def word_vector(self, word):
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def similarity(self, a: str, b: str) -> float:
        ia, ib = self.vocab.index_of(a), self.vocab.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        s = np.asarray(self.syn0)
        va, vb = s[ia], s[ib]
        return float(np.dot(va, vb) /
                     max(np.linalg.norm(va) * np.linalg.norm(vb), 1e-12))
