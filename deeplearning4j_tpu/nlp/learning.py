"""SkipGram / CBOW training updates as single jitted XLA programs.

Reference: models/embeddings/learning/impl/elements/SkipGram.java:215-272 —
the reference fuses hierarchical softmax + negative sampling into the native
``AggregateSkipGram`` ND4J op (per-pair dot/axpy on syn0/syn1 rows). The
TPU-native equivalent batches B (center, context) pairs into index arrays and
executes ONE jitted step per batch: gather rows -> sigmoid dots -> scatter-add
updates (``.at[].add``, XLA scatter — duplicate indices accumulate, matching
the reference's sequential row axpys up to summation order).

Gradients are closed-form (logistic regression), not autodiff: the update is
its own derivative, and hand-coding keeps it one fused kernel.

Duplicate-row stabilisation: the reference applies pairs SEQUENTIALLY, so a
row touched by many pairs is re-read after every axpy. A batched scatter
instead accumulates all contributions computed from the SAME stale row; when
one row appears hundreds of times in a batch (tiny vocab or very frequent
word) the summed step grows with the duplicate count and training diverges
(count * lr >> 1). Every scatter below therefore caps the accumulated
per-row step at DUP_CAP effective contributions: scale = min(1, cap/count).
Rows with <= cap duplicates per batch sum exactly like the reference; hotter
rows get a bounded step (cap * lr < 1, the SGD stability region). A full
mean (1/count) is NOT used — it collapses a whole batch into one effective
step per row and stalls learning when batch >> vocab.

HS pair layout: for each center/context pair, up to L huffman (point, code)
levels with a validity mask. NS layout: K negatives per pair sampled on host
from the unigram^0.75 table (reference: InMemoryLookupTable sampling table).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


DUP_CAP = 16.0  # max effective duplicate contributions per row per batch


def _row_mean_scale(num_rows, idx, weights, cap):
    """Per-element scale min(1, cap/count), where count is how much batch
    weight lands on the element's destination row (see module docstring:
    stale-read duplicate stabilisation). idx/weights: same shape; weight 0 =
    padding. cap=inf disables the cap (pure reference-style summation — used
    by doc2vec label training, where a single row takes a full-batch
    gradient against near-frozen targets and summation is stable)."""
    cnt = jnp.zeros((num_rows,), weights.dtype).at[idx].add(weights)
    return jnp.minimum(1.0, cap / jnp.maximum(cnt[idx], 1.0))


def _segment_row_add(row_idx, updates, weights, cap, stacked):
    """Add ``updates`` into ``stacked`` rows WITHOUT a duplicate-index
    scatter: sort by destination row, per-row-count dup_cap scale, segment
    sums, then ONE scatter whose indices are provably sorted and unique.

    Rationale (historical): the round-3 hypothesis was that XLA lowers a
    duplicate-index scatter-add to a serialized per-row loop on TPU, making
    the sort-then-unique-scatter form faster. The round-4 A/B on the real
    v5e chip REFUTED this: the plain ``.at[].add`` path measures ~3x faster
    end-to-end (184k vs 49k words/s at batch 8192, 128k vs 67k at 16384 —
    profiles/chip_session_results.json), because the argsort dominates.
    ``segment_updates`` therefore defaults to False everywhere; this path
    is kept as a tested alternative for backends where duplicate scatters
    do serialize.
    Numerically identical to the `.at[].add` path up to float summation
    order (same per-element min(1, cap/count) scale as _row_mean_scale).

    row_idx [M] int32; updates [M, D] pre-masked (weight-0 elements carry a
    zero update); weights [M] (0 = padding); cap scalar or per-element [M]
    (label rows train uncapped while word rows stay capped); stacked
    [R, D]. Segments that do not exist land on distinct dummy rows past R
    (zero contribution), so indices stay unique without a dynamic segment
    count."""
    M, D = updates.shape
    R = stacked.shape[0]
    order = jnp.argsort(row_idx)
    si = row_idx[order]
    su = updates[order]
    sw = weights[order]
    sc = jnp.broadcast_to(cap, (M,))[order]
    start = jnp.concatenate([jnp.ones((1,), bool), si[1:] != si[:-1]])
    seg = jnp.cumsum(start.astype(jnp.int32)) - 1
    cnt = jax.ops.segment_sum(sw, seg, num_segments=M)
    scale = jnp.minimum(1.0, sc / jnp.maximum(cnt[seg], 1.0))
    summed = jax.ops.segment_sum(su * scale[:, None], seg, num_segments=M)
    nseg = jnp.sum(start.astype(jnp.int32))
    rep = jax.ops.segment_max(si, seg, num_segments=M)
    j = jnp.arange(M)
    rep = jnp.where(j < nseg, rep, R + j)
    padded = jnp.concatenate([stacked, jnp.zeros((M, D), stacked.dtype)])
    padded = padded.at[rep].add(summed, indices_are_sorted=True,
                                unique_indices=True)
    return padded[:R]


@partial(jax.jit, static_argnames=("use_hs", "use_ns"))
def skipgram_step(syn0, syn1, syn1neg, centers, points, codes, code_mask,
                  neg_targets, neg_labels, lr, dup_cap, *, use_hs: bool,
                  use_ns: bool):
    """One batched skipgram update.

    syn0: [V, D] input vectors; syn1: [V, D] HS inner nodes; syn1neg: [V, D].
    centers: [B] int32 — the word whose syn0 row moves.
    points/codes/code_mask: [B, L] — HS path (padded).
    neg_targets: [B, 1+K] (positive target first), neg_labels: [B, 1+K].
    Returns updated (syn0, syn1, syn1neg).
    """
    V = syn0.shape[0]
    h = syn0[centers]  # [B, D]
    grad_h = jnp.zeros_like(h)

    if use_hs:
        w1 = syn1[points]  # [B, L, D]
        f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, w1))
        # g = (1 - code - f) * lr, masked (reference SkipGram HS sign form)
        g = (1.0 - codes - f) * code_mask * lr
        grad_h = grad_h + jnp.einsum("bl,bld->bd", g, w1)
        dw1 = jnp.einsum("bl,bd->bld", g, h)
        s1 = _row_mean_scale(V, points, code_mask, dup_cap)
        syn1 = syn1.at[points].add(dw1 * s1[..., None])

    if use_ns:
        wn = syn1neg[neg_targets]  # [B, 1+K, D]
        f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, wn))
        g = (neg_labels - f) * lr
        grad_h = grad_h + jnp.einsum("bk,bkd->bd", g, wn)
        dwn = jnp.einsum("bk,bd->bkd", g, h)
        sn = _row_mean_scale(V, neg_targets,
                             jnp.ones(neg_targets.shape, syn0.dtype),
                             dup_cap)
        syn1neg = syn1neg.at[neg_targets].add(dwn * sn[..., None])

    s0 = _row_mean_scale(V, centers, jnp.ones(centers.shape, syn0.dtype),
                         dup_cap)
    syn0 = syn0.at[centers].add(grad_h * s0[:, None])
    return syn0, syn1, syn1neg


@partial(jax.jit,
         static_argnames=("window", "batch", "neg_k", "use_hs", "use_ns",
                          "segment_updates"),
         donate_argnums=(0, 1, 2))
def skipgram_corpus_epoch(syn0, syn1, syn1neg, tokens, key,
                          lr_start, lr_end, dup_cap, points_tab, codes_tab,
                          cmask_tab, neg_table, *, window: int, batch: int,
                          neg_k: int, use_hs: bool, use_ns: bool,
                          segment_updates: bool = False):
    """One skipgram epoch generated AND trained on device.

    The round-3 v1 fast path staged pre-built pair/negative batches from
    host, but the host->device link is the scarce resource (the reference's
    AggregateSkipGram runs host-side so never pays it): ~25 bytes/pair of
    wire traffic capped throughput far below device speed. This kernel
    uploads only the TOKEN STREAM (4 bytes/token + sentence ids) and derives
    everything else on device:

    - pairs: per-offset shifted views of the padded token stream, validity =
      same sentence AND |offset| <= per-position random window
      (win = window - rand % window, the reference's shrinking window),
      laid out corpus-ordered [N, 2W] -> [S, B];
    - negatives: unigram^0.75 table lookups with jax.random, per batch;
    - HS paths: gathers from device-resident [V, L] huffman tables;
    - LR: linear lr_start -> lr_end across the S batches.

    tokens: [N] int32 stream with -1 as sentence separator AND tail
    padding, sized so N*2W % batch == 0 (separator/padding positions
    produce pair_mask 0; sentence ids are a device-side cumsum over the
    separators). Per-batch update math matches ``skipgram_step`` (same
    dup-cap stabilisation).
    """
    N = tokens.shape[0]
    W = window
    kw, kn = jax.random.split(key)
    win = jax.random.randint(kw, (N,), 1, W + 1, dtype=jnp.int32)
    sent_id = jnp.cumsum((tokens < 0).astype(jnp.int32))
    tok_pad = jnp.pad(tokens, W, constant_values=-1)
    sid_pad = jnp.pad(sent_id, W, constant_values=-2)
    ctxs, valids = [], []
    for d in range(-W, W + 1):
        if d == 0:
            continue
        ctx_d = jax.lax.dynamic_slice(tok_pad, (W + d,), (N,))
        sid_d = jax.lax.dynamic_slice(sid_pad, (W + d,), (N,))
        valids.append((sid_d == sent_id) & (jnp.abs(d) <= win)
                      & (tokens >= 0) & (ctx_d >= 0))
        ctxs.append(ctx_d)
    ctx = jnp.stack(ctxs, 1)                       # [N, 2W] corpus order
    val = jnp.stack(valids, 1)
    P = N * 2 * W
    S = P // batch
    # rows that move = context words; predicted = centers (reference
    # SkipGram iterateSample(currentWord=center, lastWord=context)
    # updates syn0[lastWord])
    rows = jnp.maximum(ctx, 0).reshape(S, batch)
    pred = jnp.broadcast_to(tokens[:, None], ctx.shape)
    pred = jnp.maximum(pred, 0).reshape(S, batch)
    pm = val.reshape(S, batch).astype(syn0.dtype)
    lrs = jnp.linspace(lr_start, lr_end, S).astype(syn0.dtype)
    return _pair_scan(syn0, syn1, syn1neg, rows, pred, pm, lrs, kn,
                      points_tab, codes_tab, cmask_tab, neg_table, dup_cap,
                      dup_cap, batch=batch, neg_k=neg_k, use_hs=use_hs,
                      use_ns=use_ns, segment_updates=segment_updates)


def _pair_scan(syn0, syn1, syn1neg, rows, pred, pm, lrs, kn, points_tab,
               codes_tab, cmask_tab, neg_table, dup_cap, syn0_cap, *,
               batch: int, neg_k: int, use_hs: bool, use_ns: bool,
               segment_updates: bool):
    """The skipgram family's inner loop: scan over [S, B] (row, predicted)
    pair batches. ``rows`` move in syn0 (skipgram: context words; DBOW: doc
    labels); ``pred`` supply the HS path / NS positive. syn0_cap is the
    dup-cap for syn0 row updates, separate from the table cap so label
    training (one row in every pair of a batch) can run uncapped
    (syn0_cap=inf) while hot word targets stay stabilised."""
    V = syn0.shape[0]
    V1 = syn1.shape[0]
    tsize = neg_table.shape[0]
    S = rows.shape[0]

    def body(carry, xs):
        syn0, syn1, syn1neg = carry
        c, p_idx, pm_b, lr, i = xs
        h = syn0[c]
        grad_h = jnp.zeros_like(h)
        # segment_updates=True: collect (destination row in the STACKED
        # [syn0; syn1; syn1neg] row space, update, weight, cap) tuples and
        # apply them in one sorted-unique scatter at the end (see
        # _segment_row_add); False keeps the plain scatter-adds for A/B.
        idx_parts, upd_parts, w_parts, cap_parts = [], [], [], []
        if use_hs:
            pts = points_tab[p_idx]                # [B, L]
            cd = codes_tab[p_idx]
            cm = cmask_tab[p_idx] * pm_b[:, None]
            w1 = syn1[pts]
            f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, w1))
            g = (1.0 - cd - f) * cm * lr
            grad_h = grad_h + jnp.einsum("bl,bld->bd", g, w1)
            dw1 = jnp.einsum("bl,bd->bld", g, h)
            if segment_updates:
                idx_parts.append(pts.reshape(-1) + V)
                upd_parts.append(dw1.reshape(-1, h.shape[1]))
                w_parts.append(cm.reshape(-1))
                cap_parts.append(jnp.full((pts.size,), dup_cap, syn0.dtype))
            else:
                s1 = _row_mean_scale(V, pts, cm, dup_cap)
                syn1 = syn1.at[pts].add(dw1 * s1[..., None])
        if use_ns:
            draws = jax.random.randint(jax.random.fold_in(kn, i),
                                       (batch, neg_k), 0, tsize,
                                       dtype=jnp.int32)
            nt = jnp.concatenate([p_idx[:, None], neg_table[draws]], axis=1)
            nl = jnp.zeros((batch, 1 + neg_k), syn0.dtype).at[:, 0].set(1.0)
            wn = syn1neg[nt]
            f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, wn))
            g = (nl - f) * pm_b[:, None] * lr
            grad_h = grad_h + jnp.einsum("bk,bkd->bd", g, wn)
            dwn = jnp.einsum("bk,bd->bkd", g, h)
            if segment_updates:
                idx_parts.append(nt.reshape(-1) + (V + V1))
                upd_parts.append(dwn.reshape(-1, h.shape[1]))
                w_parts.append(
                    jnp.broadcast_to(pm_b[:, None], nt.shape).reshape(-1))
                cap_parts.append(jnp.full((nt.size,), dup_cap, syn0.dtype))
            else:
                sn = _row_mean_scale(V, nt,
                                     jnp.broadcast_to(pm_b[:, None],
                                                      nt.shape),
                                     dup_cap)
                syn1neg = syn1neg.at[nt].add(dwn * sn[..., None])
        if segment_updates:
            idx_parts.append(c)
            upd_parts.append(grad_h)
            w_parts.append(pm_b)
            cap_parts.append(jnp.full((c.shape[0],), syn0_cap, syn0.dtype))
            stacked = jnp.concatenate([syn0, syn1, syn1neg], 0)
            stacked = _segment_row_add(jnp.concatenate(idx_parts),
                                       jnp.concatenate(upd_parts),
                                       jnp.concatenate(w_parts),
                                       jnp.concatenate(cap_parts), stacked)
            syn0 = stacked[:V]
            syn1 = stacked[V:V + V1]
            syn1neg = stacked[V + V1:]
        else:
            s0 = _row_mean_scale(V, c, pm_b, syn0_cap)
            syn0 = syn0.at[c].add(grad_h * s0[:, None])
        return (syn0, syn1, syn1neg), None

    (syn0, syn1, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg),
        (rows, pred, pm, lrs, jnp.arange(S, dtype=jnp.int32)))
    return syn0, syn1, syn1neg


@partial(jax.jit, static_argnames=("use_hs", "use_ns"))
def cbow_step(syn0, syn1, syn1neg, context, context_mask, points, codes,
              code_mask, neg_targets, neg_labels, lr, dup_cap, *,
              use_hs: bool, use_ns: bool):
    """One batched CBOW update (reference: elements/CBOW.java — the context
    mean predicts the center; the input gradient is spread over the context).

    context: [B, C] int32 context-word ids (padded), context_mask: [B, C].
    points/codes relate to the CENTER word's huffman path; neg_targets[...,0]
    is the center (label 1).
    """
    V = syn0.shape[0]
    ctx_vec = syn0[context]  # [B, C, D]
    denom = jnp.maximum(context_mask.sum(axis=1, keepdims=True), 1.0)
    h = (ctx_vec * context_mask[..., None]).sum(axis=1) / denom  # [B, D]
    grad_h = jnp.zeros_like(h)

    if use_hs:
        w1 = syn1[points]
        f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, w1))
        g = (1.0 - codes - f) * code_mask * lr
        grad_h = grad_h + jnp.einsum("bl,bld->bd", g, w1)
        s1 = _row_mean_scale(V, points, code_mask, dup_cap)
        syn1 = syn1.at[points].add(jnp.einsum("bl,bd->bld", g, h)
                                   * s1[..., None])

    if use_ns:
        wn = syn1neg[neg_targets]
        f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, wn))
        g = (neg_labels - f) * lr
        grad_h = grad_h + jnp.einsum("bk,bkd->bd", g, wn)
        sn = _row_mean_scale(V, neg_targets,
                             jnp.ones(neg_targets.shape, syn0.dtype),
                             dup_cap)
        syn1neg = syn1neg.at[neg_targets].add(jnp.einsum("bk,bd->bkd", g, h)
                                              * sn[..., None])

    # spread input gradient over contributing context words (mean -> /count),
    # then normalise duplicate context rows across the batch
    per_ctx = (grad_h[:, None, :] * context_mask[..., None]) / denom[..., None]
    sc = _row_mean_scale(V, context, context_mask, dup_cap)
    syn0 = syn0.at[context].add(per_ctx * sc[..., None])
    return syn0, syn1, syn1neg


@partial(jax.jit,
         static_argnames=("window", "batch", "neg_k", "use_hs", "use_ns",
                          "with_labels", "segment_updates"),
         donate_argnums=(0, 1, 2))
def cbow_corpus_epoch(syn0, syn1, syn1neg, tokens, labels, key, lr_start,
                      lr_end, dup_cap, label_cap, points_tab, codes_tab,
                      cmask_tab, neg_table, *, window: int, batch: int,
                      neg_k: int, use_hs: bool, use_ns: bool,
                      with_labels: bool, segment_updates: bool = False):
    """One CBOW epoch on device — and, with_labels=True, one doc2vec DM
    epoch (reference: elements/CBOW.java, sequence/DM.java).

    Same token-stream-only contract as ``skipgram_corpus_epoch`` (tokens
    [N] with -1 separators, N % batch == 0), with the roles flipped: every
    position is a CENTER whose context is the 2W shifted views; the
    masked context mean predicts the center. ``labels`` [N] carries a
    syn0 row id per position (-1 = none) and is prepended as an extra
    always-on context slot — the DM trick, streamed. The label slot's
    dup-cap is ``label_cap`` (inf for label training: one row per doc
    appears in EVERY window of that doc; capping would attenuate its
    gradient ~batch/cap-fold), word slots keep ``dup_cap``.
    """
    N = tokens.shape[0]
    W = window
    kw, kn = jax.random.split(key)
    win = jax.random.randint(kw, (N,), 1, W + 1, dtype=jnp.int32)
    sent_id = jnp.cumsum((tokens < 0).astype(jnp.int32))
    tok_pad = jnp.pad(tokens, W, constant_values=-1)
    sid_pad = jnp.pad(sent_id, W, constant_values=-2)
    ctxs, valids = [], []
    for d in range(-W, W + 1):
        if d == 0:
            continue
        ctx_d = jax.lax.dynamic_slice(tok_pad, (W + d,), (N,))
        sid_d = jax.lax.dynamic_slice(sid_pad, (W + d,), (N,))
        valids.append((sid_d == sent_id) & (jnp.abs(d) <= win)
                      & (tokens >= 0) & (ctx_d >= 0))
        ctxs.append(ctx_d)
    ctx = jnp.stack(ctxs, 1)                       # [N, 2W]
    val = jnp.stack(valids, 1)
    if with_labels:
        ctx = jnp.concatenate([labels[:, None], ctx], 1)
        val = jnp.concatenate([((labels >= 0) & (tokens >= 0))[:, None],
                               val], 1)
    C = ctx.shape[1]
    S = N // batch
    V = syn0.shape[0]
    V1 = syn1.shape[0]
    tsize = neg_table.shape[0]
    ctx_b = jnp.maximum(ctx, 0).reshape(S, batch, C)
    # center is trainable iff in-vocab with >=1 live context slot; context
    # slots are additionally masked by their center's validity
    pm = ((tokens >= 0) & val.any(axis=1)).astype(syn0.dtype)
    cm_b = (val.astype(syn0.dtype) * pm[:, None]).reshape(S, batch, C)
    pm_b = pm.reshape(S, batch)
    cen_b = jnp.maximum(tokens, 0).reshape(S, batch)
    lrs = jnp.linspace(lr_start, lr_end, S).astype(syn0.dtype)
    if with_labels:
        slot_cap = jnp.concatenate(
            [jnp.broadcast_to(label_cap, (1,)).astype(syn0.dtype),
             jnp.full((C - 1,), 1.0, syn0.dtype) * dup_cap])
    else:
        slot_cap = jnp.full((C,), 1.0, syn0.dtype) * dup_cap

    def body(carry, xs):
        syn0, syn1, syn1neg = carry
        cx, cm, p_idx, pm_b, lr, i = xs
        denom = jnp.maximum(cm.sum(axis=1, keepdims=True), 1.0)
        h = (syn0[cx] * cm[..., None]).sum(axis=1) / denom    # [B, D]
        grad_h = jnp.zeros_like(h)
        idx_parts, upd_parts, w_parts, cap_parts = [], [], [], []
        if use_hs:
            pts = points_tab[p_idx]
            cd = codes_tab[p_idx]
            hm = cmask_tab[p_idx] * pm_b[:, None]
            w1 = syn1[pts]
            f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, w1))
            g = (1.0 - cd - f) * hm * lr
            grad_h = grad_h + jnp.einsum("bl,bld->bd", g, w1)
            dw1 = jnp.einsum("bl,bd->bld", g, h)
            if segment_updates:
                idx_parts.append(pts.reshape(-1) + V)
                upd_parts.append(dw1.reshape(-1, h.shape[1]))
                w_parts.append(hm.reshape(-1))
                cap_parts.append(jnp.full((pts.size,), 1.0, syn0.dtype)
                                 * dup_cap)
            else:
                s1 = _row_mean_scale(V, pts, hm, dup_cap)
                syn1 = syn1.at[pts].add(dw1 * s1[..., None])
        if use_ns:
            draws = jax.random.randint(jax.random.fold_in(kn, i),
                                       (batch, neg_k), 0, tsize,
                                       dtype=jnp.int32)
            nt = jnp.concatenate([p_idx[:, None], neg_table[draws]], axis=1)
            nl = jnp.zeros((batch, 1 + neg_k), syn0.dtype).at[:, 0].set(1.0)
            wn = syn1neg[nt]
            f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, wn))
            g = (nl - f) * pm_b[:, None] * lr
            grad_h = grad_h + jnp.einsum("bk,bkd->bd", g, wn)
            dwn = jnp.einsum("bk,bd->bkd", g, h)
            if segment_updates:
                idx_parts.append(nt.reshape(-1) + (V + V1))
                upd_parts.append(dwn.reshape(-1, h.shape[1]))
                w_parts.append(
                    jnp.broadcast_to(pm_b[:, None], nt.shape).reshape(-1))
                cap_parts.append(jnp.full((nt.size,), 1.0, syn0.dtype)
                                 * dup_cap)
            else:
                sn = _row_mean_scale(V, nt,
                                     jnp.broadcast_to(pm_b[:, None],
                                                      nt.shape),
                                     dup_cap)
                syn1neg = syn1neg.at[nt].add(dwn * sn[..., None])
        # spread the input gradient over contributing context slots
        per_ctx = (grad_h[:, None, :] * cm[..., None]) / denom[..., None]
        cap_b = jnp.broadcast_to(slot_cap[None, :], cm.shape)
        if segment_updates:
            idx_parts.append(cx.reshape(-1))
            upd_parts.append(per_ctx.reshape(-1, h.shape[1]))
            w_parts.append(cm.reshape(-1))
            cap_parts.append(cap_b.reshape(-1))
            stacked = jnp.concatenate([syn0, syn1, syn1neg], 0)
            stacked = _segment_row_add(jnp.concatenate(idx_parts),
                                       jnp.concatenate(upd_parts),
                                       jnp.concatenate(w_parts),
                                       jnp.concatenate(cap_parts), stacked)
            syn0 = stacked[:V]
            syn1 = stacked[V:V + V1]
            syn1neg = stacked[V + V1:]
        else:
            sc = _row_mean_scale(V, cx, cm, cap_b)
            syn0 = syn0.at[cx].add(per_ctx * sc[..., None])
        return (syn0, syn1, syn1neg), None

    (syn0, syn1, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg),
        (ctx_b, cm_b, cen_b, pm_b, lrs, jnp.arange(S, dtype=jnp.int32)))
    return syn0, syn1, syn1neg


@partial(jax.jit,
         static_argnames=("batch", "neg_k", "use_hs", "use_ns",
                          "segment_updates"),
         donate_argnums=(0, 1, 2))
def dbow_corpus_epoch(syn0, syn1, syn1neg, tokens, labels, key, lr_start,
                      lr_end, dup_cap, label_cap, points_tab, codes_tab,
                      cmask_tab, neg_table, *, batch: int, neg_k: int,
                      use_hs: bool, use_ns: bool,
                      segment_updates: bool = False):
    """One doc2vec DBOW epoch on device (reference: sequence/DBOW.java):
    the document's label row predicts every document word — the skipgram
    inner loop with rows = ``labels`` [N] (syn0 row per position, -1 =
    none) and predicted = ``tokens``. Label syn0 updates run with
    ``label_cap`` (inf: full-batch gradient on the one moving row); word
    HS/NS tables keep ``dup_cap``."""
    N = tokens.shape[0]
    S = N // batch
    _, kn = jax.random.split(key)
    pm = ((tokens >= 0) & (labels >= 0)).astype(syn0.dtype).reshape(S, batch)
    rows = jnp.maximum(labels, 0).reshape(S, batch)
    pred = jnp.maximum(tokens, 0).reshape(S, batch)
    lrs = jnp.linspace(lr_start, lr_end, S).astype(syn0.dtype)
    return _pair_scan(syn0, syn1, syn1neg, rows, pred, pm, lrs, kn,
                      points_tab, codes_tab, cmask_tab, neg_table, dup_cap,
                      label_cap, batch=batch, neg_k=neg_k, use_hs=use_hs,
                      use_ns=use_ns, segment_updates=segment_updates)


class BatchBuilder:
    """Host-side pair/batch assembly shared by the elements learners.

    Converts tokenized sentences into padded index arrays for the jitted
    steps; implements the reference's dynamic window (b = rand % window),
    subsampling, and unigram^0.75 negative table (reference:
    InMemoryLookupTable.java:55-97,120 makeTable / SkipGram.java:215-224)."""

    def __init__(self, cache, window=5, negative=0, use_hs=True,
                 sampling=0.0, table_size=None, seed=12345,
                 max_code_length=40):
        self.cache = cache
        self.window = window
        self.negative = int(negative)
        self.use_hs = use_hs
        self.sampling = sampling
        self.rng = np.random.RandomState(seed)
        self.max_code_len = max(
            (len(cache.element_at_index(i).codes)
             for i in range(cache.num_words())), default=1) or 1
        self.max_code_len = min(self.max_code_len, max_code_length)
        counts = cache.counts_array()
        if table_size is None:
            # ~32 slots per word on average (capped) so even unigram^0.75
            # tail words keep a nonzero draw probability; the reference's
            # table is a fixed 1e8 entries (InMemoryLookupTable), far more
            # memory for the same quantisation role
            table_size = int(min(max(100000, 32 * cache.num_words()),
                                 1 << 24))
        if self.negative > 0 and counts.size:
            p = counts ** 0.75
            self._neg_cum = np.cumsum(p / p.sum())
            # quantised unigram^0.75 table (reference
            # InMemoryLookupTable.makeTable): sampling = one randint + one
            # gather instead of a searchsorted per draw
            self._neg_table = np.searchsorted(
                self._neg_cum,
                (np.arange(table_size) + 0.5) / table_size).astype(np.int32)
        else:
            self._neg_cum = None
            self._neg_table = None
        # precomputed huffman path arrays [V, L]
        V = cache.num_words()
        L = self.max_code_len
        self.points = np.zeros((V, L), np.int32)
        self.codes = np.zeros((V, L), np.float32)
        self.code_mask = np.zeros((V, L), np.float32)
        for i in range(V):
            w = cache.element_at_index(i)
            n = min(len(w.codes), L)
            if n:
                self.points[i, :n] = w.points[:n]
                self.codes[i, :n] = w.codes[:n]
                self.code_mask[i, :n] = 1.0

    def lookup_indices(self, tokens) -> np.ndarray:
        """Vocab indices for in-vocab tokens, NO subsampling (callers that
        train multiple epochs re-draw subsampling per epoch)."""
        idx = [self.cache.index_of(t) for t in tokens]
        return np.array([i for i in idx if i >= 0], np.int32)

    def subsample(self, idx: np.ndarray) -> np.ndarray:
        """One frequency-subsampling draw (word2vec keep probability)."""
        if self.sampling <= 0 or not idx.size:
            return idx
        counts = self.cache.counts_array()
        total = self.cache.total_word_count
        freq = counts[idx] / total
        keep_p = (np.sqrt(freq / self.sampling) + 1) * self.sampling / freq
        return idx[self.rng.random_sample(idx.size) < keep_p]

    def sentence_to_indices(self, tokens) -> np.ndarray:
        return self.subsample(self.lookup_indices(tokens))

    def pairs_from_sentence(self, idx: np.ndarray):
        """(centers, contexts) for one sentence — same shrinking random
        window as the corpus-level path (single source of truth)."""
        return self.pairs_from_corpus([idx])

    def pairs_from_corpus(self, sent_indices):
        """All (center, context) pairs of a corpus in one vectorised pass.

        ``sent_indices``: list of per-sentence index arrays. Same shrinking
        random window as ``pairs_from_sentence`` (b = rand % window), but one
        boolean mask per offset over the WHOLE concatenated corpus — the
        per-sentence Python loop disappears. Sentence boundaries are enforced
        by comparing the shifted position against each token's own sentence
        start/end."""
        sent_indices = [s for s in sent_indices if s.size]
        if not sent_indices:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        lens = np.array([s.size for s in sent_indices])
        idx = np.concatenate(sent_indices).astype(np.int32)
        n = idx.size
        starts = np.repeat(np.cumsum(lens) - lens, lens)   # [n] own-sentence start
        ends = starts + np.repeat(lens, lens)              # [n] own-sentence end
        pos = np.arange(n)
        win = self.window - self.rng.randint(0, self.window, size=n)
        centers, contexts = [], []
        for d in range(-self.window, self.window + 1):
            if d == 0:
                continue
            j = pos + d
            m = (np.abs(d) <= win) & (j >= starts) & (j < ends)
            if m.any():
                centers.append(idx[m])
                contexts.append(idx[j[m]])
        if not centers:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        return (np.concatenate(centers), np.concatenate(contexts))

    def sample_negatives(self, positives: np.ndarray,
                         rng: Optional[np.random.RandomState] = None
                         ) -> np.ndarray:
        """[B] -> [B, 1+K] target ids, positive first. ``rng`` overrides the
        builder's stream (deterministic inference)."""
        B, K = positives.size, self.negative
        targets = np.empty((B, 1 + K), np.int32)
        targets[:, 0] = positives
        if K:
            draws = (rng or self.rng).randint(
                0, self._neg_table.size, size=(B, K))
            targets[:, 1:] = self._neg_table[draws]
        return targets

    def neg_labels(self, B: int) -> np.ndarray:
        lab = np.zeros((B, 1 + self.negative), np.float32)
        lab[:, 0] = 1.0
        return lab

    def hs_arrays(self, predicted: np.ndarray):
        """Huffman paths for the predicted words: ([B,L] points, codes, mask)."""
        return (self.points[predicted], self.codes[predicted],
                self.code_mask[predicted])
