"""SkipGram / CBOW training updates as single jitted XLA programs.

Reference: models/embeddings/learning/impl/elements/SkipGram.java:215-272 —
the reference fuses hierarchical softmax + negative sampling into the native
``AggregateSkipGram`` ND4J op (per-pair dot/axpy on syn0/syn1 rows). The
TPU-native equivalent batches B (center, context) pairs into index arrays and
executes ONE jitted step per batch: gather rows -> sigmoid dots -> scatter-add
updates (``.at[].add``, XLA scatter — duplicate indices accumulate, matching
the reference's sequential row axpys up to summation order).

Gradients are closed-form (logistic regression), not autodiff: the update is
its own derivative, and hand-coding keeps it one fused kernel.

HS pair layout: for each center/context pair, up to L huffman (point, code)
levels with a validity mask. NS layout: K negatives per pair sampled on host
from the unigram^0.75 table (reference: InMemoryLookupTable sampling table).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("use_hs", "use_ns"))
def skipgram_step(syn0, syn1, syn1neg, centers, points, codes, code_mask,
                  neg_targets, neg_labels, lr, *, use_hs: bool, use_ns: bool):
    """One batched skipgram update.

    syn0: [V, D] input vectors; syn1: [V, D] HS inner nodes; syn1neg: [V, D].
    centers: [B] int32 — the word whose syn0 row moves.
    points/codes/code_mask: [B, L] — HS path (padded).
    neg_targets: [B, 1+K] (positive target first), neg_labels: [B, 1+K].
    Returns updated (syn0, syn1, syn1neg).
    """
    h = syn0[centers]  # [B, D]
    grad_h = jnp.zeros_like(h)

    if use_hs:
        w1 = syn1[points]  # [B, L, D]
        f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, w1))
        # g = (1 - code - f) * lr, masked (reference SkipGram HS sign form)
        g = (1.0 - codes - f) * code_mask * lr
        grad_h = grad_h + jnp.einsum("bl,bld->bd", g, w1)
        dw1 = jnp.einsum("bl,bd->bld", g, h)
        syn1 = syn1.at[points].add(dw1)

    if use_ns:
        wn = syn1neg[neg_targets]  # [B, 1+K, D]
        f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, wn))
        g = (neg_labels - f) * lr
        grad_h = grad_h + jnp.einsum("bk,bkd->bd", g, wn)
        dwn = jnp.einsum("bk,bd->bkd", g, h)
        syn1neg = syn1neg.at[neg_targets].add(dwn)

    syn0 = syn0.at[centers].add(grad_h)
    return syn0, syn1, syn1neg


@partial(jax.jit, static_argnames=("use_hs", "use_ns"))
def cbow_step(syn0, syn1, syn1neg, context, context_mask, points, codes,
              code_mask, neg_targets, neg_labels, lr, *, use_hs: bool,
              use_ns: bool):
    """One batched CBOW update (reference: elements/CBOW.java — the context
    mean predicts the center; the input gradient is spread over the context).

    context: [B, C] int32 context-word ids (padded), context_mask: [B, C].
    points/codes relate to the CENTER word's huffman path; neg_targets[...,0]
    is the center (label 1).
    """
    ctx_vec = syn0[context]  # [B, C, D]
    denom = jnp.maximum(context_mask.sum(axis=1, keepdims=True), 1.0)
    h = (ctx_vec * context_mask[..., None]).sum(axis=1) / denom  # [B, D]
    grad_h = jnp.zeros_like(h)

    if use_hs:
        w1 = syn1[points]
        f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, w1))
        g = (1.0 - codes - f) * code_mask * lr
        grad_h = grad_h + jnp.einsum("bl,bld->bd", g, w1)
        syn1 = syn1.at[points].add(jnp.einsum("bl,bd->bld", g, h))

    if use_ns:
        wn = syn1neg[neg_targets]
        f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, wn))
        g = (neg_labels - f) * lr
        grad_h = grad_h + jnp.einsum("bk,bkd->bd", g, wn)
        syn1neg = syn1neg.at[neg_targets].add(jnp.einsum("bk,bd->bkd", g, h))

    # spread input gradient over contributing context words (mean -> /count)
    per_ctx = (grad_h[:, None, :] * context_mask[..., None]) / denom[..., None]
    syn0 = syn0.at[context].add(per_ctx)
    return syn0, syn1, syn1neg


class BatchBuilder:
    """Host-side pair/batch assembly shared by the elements learners.

    Converts tokenized sentences into padded index arrays for the jitted
    steps; implements the reference's dynamic window (b = rand % window),
    subsampling, and unigram^0.75 negative table (reference:
    InMemoryLookupTable.java:55-97,120 makeTable / SkipGram.java:215-224)."""

    def __init__(self, cache, window=5, negative=0, use_hs=True,
                 sampling=0.0, table_size=100000, seed=12345,
                 max_code_length=40):
        self.cache = cache
        self.window = window
        self.negative = int(negative)
        self.use_hs = use_hs
        self.sampling = sampling
        self.rng = np.random.RandomState(seed)
        self.max_code_len = max(
            (len(cache.element_at_index(i).codes)
             for i in range(cache.num_words())), default=1) or 1
        self.max_code_len = min(self.max_code_len, max_code_length)
        counts = cache.counts_array()
        if self.negative > 0 and counts.size:
            p = counts ** 0.75
            self._neg_cum = np.cumsum(p / p.sum())
        else:
            self._neg_cum = None
        # precomputed huffman path arrays [V, L]
        V = cache.num_words()
        L = self.max_code_len
        self.points = np.zeros((V, L), np.int32)
        self.codes = np.zeros((V, L), np.float32)
        self.code_mask = np.zeros((V, L), np.float32)
        for i in range(V):
            w = cache.element_at_index(i)
            n = min(len(w.codes), L)
            if n:
                self.points[i, :n] = w.points[:n]
                self.codes[i, :n] = w.codes[:n]
                self.code_mask[i, :n] = 1.0

    def sentence_to_indices(self, tokens) -> np.ndarray:
        idx = [self.cache.index_of(t) for t in tokens]
        idx = np.array([i for i in idx if i >= 0], np.int32)
        if self.sampling > 0 and idx.size:
            counts = self.cache.counts_array()
            total = self.cache.total_word_count
            freq = counts[idx] / total
            # word2vec subsampling keep probability
            keep_p = (np.sqrt(freq / self.sampling) + 1) * self.sampling / freq
            idx = idx[self.rng.random_sample(idx.size) < keep_p]
        return idx

    def pairs_from_sentence(self, idx: np.ndarray):
        """(centers, contexts) with the reference's shrinking random window
        (b = rand % window), vectorised: one boolean mask per offset d in
        [-window, window] instead of a per-word python loop."""
        n = idx.size
        if n < 2:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        win = self.window - self.rng.randint(0, self.window, size=n)  # [n]
        pos = np.arange(n)
        centers, contexts = [], []
        for d in range(-self.window, self.window + 1):
            if d == 0:
                continue
            j = pos + d
            m = (np.abs(d) <= win) & (j >= 0) & (j < n)
            if m.any():
                centers.append(idx[pos[m]])
                contexts.append(idx[j[m]])
        if not centers:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        return (np.concatenate(centers).astype(np.int32),
                np.concatenate(contexts).astype(np.int32))

    def sample_negatives(self, positives: np.ndarray,
                         rng: Optional[np.random.RandomState] = None
                         ) -> np.ndarray:
        """[B] -> [B, 1+K] target ids, positive first. ``rng`` overrides the
        builder's stream (deterministic inference)."""
        B, K = positives.size, self.negative
        targets = np.empty((B, 1 + K), np.int32)
        targets[:, 0] = positives
        if K:
            u = (rng or self.rng).random_sample((B, K))
            targets[:, 1:] = np.searchsorted(self._neg_cum, u).astype(np.int32)
        return targets

    def neg_labels(self, B: int) -> np.ndarray:
        lab = np.zeros((B, 1 + self.negative), np.float32)
        lab[:, 0] = 1.0
        return lab

    def hs_arrays(self, predicted: np.ndarray):
        """Huffman paths for the predicted words: ([B,L] points, codes, mask)."""
        return (self.points[predicted], self.codes[predicted],
                self.code_mask[predicted])
