"""ParagraphVectors / doc2vec (reference:
models/paragraphvectors/ParagraphVectors.java:47, sequence learning
impls models/embeddings/learning/impl/sequence/DBOW.java + DM.java).

Labels live in the same lookup table as words (reference behavior): each
label gets a vocab entry and a syn0 row. DBOW: the label row predicts each
word of its document through the word's HS path / negatives — exactly the
skipgram step with the label as the moving row. DM: the label is prepended
to every CBOW context window.

``infer_vector`` trains a fresh row against frozen syn1/syn1neg (reference:
ParagraphVectors.inferVector), as one jitted loop per iteration.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.learning import skipgram_step
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabWord


@partial(jax.jit, static_argnames=("use_hs", "use_ns"))
def _infer_step(vec, syn1, syn1neg, points, codes, code_mask, neg_targets,
                neg_labels, lr, *, use_hs: bool, use_ns: bool):
    """DBOW inference: move only ``vec`` [D]; syn1/syn1neg frozen."""
    grad = jnp.zeros_like(vec)
    if use_hs:
        w1 = syn1[points]  # [B, L, D]
        f = jax.nn.sigmoid(jnp.einsum("d,bld->bl", vec, w1))
        g = (1.0 - codes - f) * code_mask * lr
        grad = grad + jnp.einsum("bl,bld->d", g, w1)
    if use_ns:
        wn = syn1neg[neg_targets]
        f = jax.nn.sigmoid(jnp.einsum("d,bkd->bk", vec, wn))
        g = (neg_labels - f) * lr
        grad = grad + jnp.einsum("bk,bkd->d", g, wn)
    return vec + grad


class ParagraphVectors(SequenceVectors):
    """reference: ParagraphVectors.java:47 (builder + inferVector :~300)."""

    LABEL_PREFIX = "__label__"

    def __init__(self, sequence_algorithm: str = "dbow",
                 train_words: bool = False, **kw):
        kw.setdefault("elements_algorithm", "skipgram")
        super().__init__(**kw)
        self.sequence_algorithm = sequence_algorithm.lower()
        self.train_words = train_words
        self._label_ids: dict = {}

    # ------------------------------------------------------------------ vocab
    def _label_token(self, label: str) -> str:
        return self.LABEL_PREFIX + label

    def build_vocab_from_documents(self, documents) -> None:
        contents = [d.content for d in documents]
        self.build_vocab(contents)
        # add labels to the vocab (no huffman path needed for labels — they
        # are never predicted, only predictors), then rebuild indices+tree
        for d in documents:
            for label in d.labels:
                t = self._label_token(label)
                if not self.vocab.contains_word(t):
                    self.vocab.add_token(VocabWord(t, 1.0))
        self.vocab.update_indices()
        Huffman(self.vocab).build()

    # -------------------------------------------------------------------- fit
    def fit(self, documents) -> "ParagraphVectors":
        documents = list(documents)
        if self.vocab is None:
            self.build_vocab_from_documents(documents)
        if self.syn0 is None:
            self.reset_weights()
        self._label_ids = {
            label: self.vocab.index_of(self._label_token(label))
            for d in documents for label in d.labels}
        total = max(sum(len(d.content.split()) for d in documents), 1)
        total *= self.epochs
        seen = 0
        for _ in range(self.epochs):
            for d in documents:
                tokens = self.tokenizer_factory.create(d.content).tokens()
                idx = self._builder.sentence_to_indices(tokens)
                if idx.size == 0:
                    continue
                lr = self._alpha(seen / total)
                label_ids = np.asarray(
                    [self.vocab.index_of(self._label_token(l))
                     for l in d.labels], np.int32)
                if self.sequence_algorithm == "dbow":
                    self._fit_dbow(idx, label_ids, lr)
                elif self.sequence_algorithm == "dm":
                    self._fit_dm(idx, label_ids, lr)
                else:
                    raise ValueError(
                        f"Unknown sequence algorithm "
                        f"'{self.sequence_algorithm}'")
                if self.train_words:
                    self._train_indexed(idx, seen / total)
                seen += idx.size
        return self

    def _fit_dbow(self, idx, label_ids, lr):
        """Label row predicts every doc word (reference: DBOW.java).

        dup_cap=inf: the whole batch moves ONE label row, so the duplicate
        cap would attenuate label training ~batch/16-fold; uncapped
        summation is the full-batch gradient for that single row against
        near-frozen word targets — stable, and matches the reference's
        sequential accumulation."""
        for lab in label_ids:
            rows = np.full(idx.size, lab, np.int32)
            for s in range(0, idx.size, self.batch_size):
                sl = slice(s, s + self.batch_size)
                self._skipgram_batch(rows[sl], idx[sl], lr,
                                     dup_cap=float("inf"))

    def _train_indexed(self, idx, progress):
        """trainWords=true: ordinary skipgram over the document's words
        (reference: ParagraphVectors trainWords flag). Sliced to batch_size
        like _fit_dbow so XLA shapes stay bounded instead of specialising
        on every document's pair count."""
        centers, contexts = self._builder.pairs_from_sentence(idx)
        lr = self._alpha(progress)
        for s in range(0, centers.size, self.batch_size):
            sl = slice(s, s + self.batch_size)
            self._skipgram_batch(contexts[sl], centers[sl], lr)

    def _fit_dm(self, idx, label_ids, lr):
        """Label + window context predicts center (reference: DM.java).
        dup_cap=inf for the same reason as DBOW (label id appears in every
        context window)."""
        for lab in label_ids:
            extra = np.full(idx.size, lab, np.int32)
            self._cbow_sentence(idx, lr, extra_context=extra,
                                dup_cap=float("inf"))

    # ------------------------------------------------------------- inference
    def infer_vector(self, text: str, learning_rate: float = 0.01,
                     iterations: int = 5, seed: int = 0) -> np.ndarray:
        """Train a fresh paragraph vector for unseen text (reference:
        ParagraphVectors.inferVector)."""
        tokens = self.tokenizer_factory.create(text).tokens()
        idx = self._builder.sentence_to_indices(tokens)
        rng = np.random.RandomState(seed)
        vec = jnp.asarray(
            (rng.random_sample(self.layer_size) - 0.5) / self.layer_size,
            jnp.float32)
        if idx.size == 0:
            return np.asarray(vec)
        b = self._builder
        points, codes, mask = b.hs_arrays(idx)
        neg_rng = np.random.RandomState(seed + 1)
        for _ in range(iterations):
            negs = b.sample_negatives(idx, rng=neg_rng)
            vec = _infer_step(vec, self.syn1, self.syn1neg,
                              jnp.asarray(points), jnp.asarray(codes),
                              jnp.asarray(mask), jnp.asarray(negs),
                              jnp.asarray(b.neg_labels(idx.size)),
                              jnp.float32(learning_rate),
                              use_hs=self.use_hs, use_ns=self.negative > 0)
        return np.asarray(vec)

    # ------------------------------------------------------------- query API
    def labels(self) -> list:
        return list(self._label_ids)

    def label_vector(self, label: str) -> np.ndarray:
        return np.asarray(self.syn0[self._label_ids[label]])

    def similarity_to_label(self, text: str, label: str) -> float:
        v = self.infer_vector(text)
        lv = self.label_vector(label)
        denom = max(np.linalg.norm(v) * np.linalg.norm(lv), 1e-12)
        return float(np.dot(v, lv) / denom)

    def predict(self, text: str) -> str:
        """Nearest label for unseen text (reference:
        ParagraphVectors.predict)."""
        v = self.infer_vector(text)
        best, best_sim = None, -2.0
        for label in self._label_ids:
            lv = self.label_vector(label)
            denom = max(np.linalg.norm(v) * np.linalg.norm(lv), 1e-12)
            sim = float(np.dot(v, lv) / denom)
            if sim > best_sim:
                best, best_sim = label, sim
        return best
